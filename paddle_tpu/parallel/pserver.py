"""Parameter-server variable transport: send/recv/listen_and_serv.

Reference: /root/reference/paddle/fluid/operators/send_op.cc:44,
recv_op.cc:28, listen_and_serv_op.cc:56 and detail/{grpc_client,
grpc_server,send_recv.proto,sendrecvop_utils.cc} — trainers push grad
blocks to pservers, a fan-in barrier triggers the optimize block, then
trainers pull updated params.

TPU-native position (SURVEY.md §5.8): the *recommended* data-parallel path
is psum over ICI (parallel.ParallelExecutor) — this module exists for the
reference's multi-process workflow parity: host-side feed/eval transfer and
CPU-cluster pserver training.  Transport is a length-prefixed JSON+raw
frame over TCP instead of gRPC VariableMessage; semantics (per-trainer grad
rename `%s.trainer_%d`, batch barrier fan-in, blocking Get until the
optimize block ran) mirror listen_and_serv_op.cc:78-175.
"""
from __future__ import annotations

import atexit
import json
import os
import socket
import struct
import threading
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..core.lod import LoDTensor, SelectedRows
from ..core.resilience import (RetryPolicy, fault_injector,
                               sched_fault_armed as _sched_fault)
from ..observability import attribution as obs_attr
from ..observability import flightrecorder
from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing

__all__ = ["VariableServer", "VariableClient", "BarrierTimeoutError",
           "serialize_var", "deserialize_var", "serialize_var_parts",
           "serialize_batch_parts", "deserialize_batch",
           "prebind_endpoint", "discard_prebound"]

_HDR = struct.Struct("<I")

# transport telemetry (gated by PADDLE_TPU_METRICS); trace context rides
# the frame head's optional "trace" field, so a trainer-side span and
# the pserver-side handling span share one trace id
_M_BYTES_SENT = obs_metrics.counter(
    "paddle_tpu_pserver_bytes_sent_total",
    "frame bytes written to pserver connections (both roles)")
_M_BYTES_RECV = obs_metrics.counter(
    "paddle_tpu_pserver_bytes_recv_total",
    "frame bytes read from pserver connections (both roles)")
_M_REQUESTS = obs_metrics.counter(
    "paddle_tpu_pserver_requests_total",
    "server-side requests handled, by verb", ("verb",))
_M_BARRIER_WAIT = obs_metrics.histogram(
    "paddle_tpu_pserver_barrier_wait_seconds",
    "client wall time blocked in send_batch_barrier (fan-in + optimize)")
_M_OPTIMIZE_SECONDS = obs_metrics.histogram(
    "paddle_tpu_pserver_optimize_seconds",
    "server-side fan-in grad merge + optimize-program latency")
# fused-transfer telemetry (parallel/comm.py carries the per-round
# latency/bytes histograms; these profile the bucket packer itself)
_M_BUCKET_VARS = obs_metrics.histogram(
    "paddle_tpu_comm_bucket_vars",
    "variables fused into one SEND_BATCH bucket",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
_M_BUCKET_FILL = obs_metrics.histogram(
    "paddle_tpu_comm_bucket_fill",
    "bucket payload bytes / comm_bucket_bytes cap (>1: one oversized "
    "var shipped alone)",
    buckets=(0.0625, 0.125, 0.25, 0.5, 0.75, 1.0, 2.0))
_M_BATCH_FALLBACK = obs_metrics.counter(
    "paddle_tpu_comm_batch_fallback_total",
    "batch-capable clients that dropped to per-var frames after a "
    "legacy server rejected SEND_BATCH/GET_BATCH")

_KNOWN_VERBS = frozenset(
    {"HELLO", "SEND", "SEND_BATCH", "BARRIER", "GET", "GET_BATCH",
     "STOP", "OK", "ERR", "VAR", "VARS",
     # elastic cluster runtime (docs/resilience.md "Elastic clusters"):
     # PUT_BATCH installs values under their CANONICAL names (shard
     # migration / trainer-held recovery), DROP erases migrated-away
     # vars, HAVE probes which names a member holds (bootstrap-copy
     # consolidation), FENCE/COMMIT are the controller's two-phase
     # view change
     "PUT_BATCH", "DROP", "HAVE", "FENCE", "COMMIT",
     # FLIGHT returns the process flight-recorder ring on demand
     # (observability/flightrecorder.py) — post-mortems of a LIVE but
     # misbehaving pserver without attaching a debugger
     "FLIGHT"})

# frame-length sanity: a header larger than 1 MiB or a payload larger
# than 2 GiB is protocol desync / corruption, not a real request —
# reject instead of allocating huge buffers or blocking on bytes that
# will never arrive
_MAX_HEAD = 1 << 20
_MAX_PAYLOAD = 1 << 31

# endpoint -> bound+listening socket, held from address PUBLICATION to
# serve(): registry-discovered pservers bind FIRST and register the
# already-owned port (the reference's etcd flow — pserver.go binds the
# service then publishes), so no other process can take it in between
_prebound: Dict[int, socket.socket] = {}


def prebind_endpoint(host: str = "127.0.0.1") -> str:
    """Bind+listen an OS-assigned port NOW and park the socket for the
    VariableServer that will later `serve(port)`; returns 'host:port'."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, 0))
    s.listen(16)
    port = s.getsockname()[1]
    _prebound[port] = s
    return f"{host}:{port}"


def _adopt_prebound(port: int):
    return _prebound.pop(port, None) if port else None


def discard_prebound(endpoint: Optional[str] = None):
    """Close parked sockets a VariableServer never adopted (one endpoint,
    or all of them) — a prebound pserver slot that was abandoned would
    otherwise hold its port until process exit."""
    if endpoint is not None:
        ports = [int(endpoint.rsplit(":", 1)[1])]
    else:
        ports = list(_prebound)
    for port in ports:
        s = _prebound.pop(port, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass


atexit.register(discard_prebound)


# ---------------------------------------------------------------------------
# wire format (reference sendrecvop_utils.cc SerializeToMessage)
# ---------------------------------------------------------------------------


def _as_u8(arr: np.ndarray) -> np.ndarray:
    """Flat uint8 view of an array's bytes — no copy once contiguous."""
    a = np.ascontiguousarray(arr)
    return a.reshape(-1).view(np.uint8)


def _blen(part) -> int:
    return part.nbytes if hasattr(part, "nbytes") else len(part)


def _join_parts(parts) -> bytes:
    return b"".join(p if isinstance(p, (bytes, bytearray)) else bytes(p)
                    for p in parts)


def serialize_var_parts(value):
    """-> (head dict, [flat-uint8 buffers]): the zero-copy wire form.
    The buffers are views over the value's own memory, written with
    scatter-gather (`_sendall_parts`) instead of `tobytes()` concat;
    joining `parts` after the JSON head reproduces the legacy
    `serialize_var` payload byte-for-byte."""
    if isinstance(value, SelectedRows):
        # sparse message: rows + row values + dense height — the
        # reference's large-model path ships sparse rows to pservers
        # (ParameterServer2::getParameterSparse, sendrecvop_utils.cc
        # SerializeToMessage's SELECTED_ROWS branch)
        rows = np.ascontiguousarray(np.asarray(value.rows))
        data = np.ascontiguousarray(np.asarray(value.value))
        head = {
            "kind": "selected_rows", "height": int(value.height),
            "rows_dtype": str(rows.dtype), "n_rows": int(rows.shape[0]),
            "dtype": str(data.dtype), "shape": list(data.shape),
        }
        return head, [_as_u8(rows), _as_u8(data)]
    if isinstance(value, LoDTensor):
        data = np.asarray(value.data)
        lod = [list(map(int, lvl)) for lvl in value.lod]
    else:
        data = np.asarray(value)
        lod = None
    head = {"dtype": str(data.dtype), "shape": list(data.shape),
            "lod": lod}
    return head, [_as_u8(data)]


def _var_payload_parts(head: dict, parts) -> list:
    hb = json.dumps(head).encode()
    return [_HDR.pack(len(hb)) + hb, *parts]


def serialize_var(value) -> bytes:
    head, parts = serialize_var_parts(value)
    return _join_parts(_var_payload_parts(head, parts))


def _batch_payload_parts(prepared) -> list:
    """`prepared`: [(name, head, parts, nbytes)] -> scatter-gather
    buffer list for one batch payload: HDR(len(bh)) + bh + concatenated
    var bytes, bh = {"vars": [{"name", "nbytes", **var_head}, ...]}."""
    heads = [{"name": n, "nbytes": nb, **h} for n, h, _, nb in prepared]
    bh = json.dumps({"vars": heads}).encode()
    out = [_HDR.pack(len(bh)), bh]
    for _, _, parts, _ in prepared:
        out.extend(parts)
    return out


def _prepare_vars(items):
    """[(name, value)] -> [(name, head, parts, nbytes)] (no copies)."""
    prepared = []
    for n, v in items:
        head, parts = serialize_var_parts(v)
        prepared.append((n, head, parts, sum(_blen(p) for p in parts)))
    return prepared


def _pack_buckets(prepared, cap):
    """DDP-style packing shared by SEND_BATCH and PUT_BATCH: arrival
    order, close a bucket when the next var would push it past the cap
    (an oversized var ships alone)."""
    buckets, cur, cur_b = [], [], 0
    for it in prepared:
        if cur and cur_b + it[3] > cap:
            buckets.append(cur)
            cur, cur_b = [], 0
        cur.append(it)
        cur_b += it[3]
    if cur:
        buckets.append(cur)
    return buckets


def serialize_batch_parts(items) -> list:
    """[(name, value)] -> buffer list for one SEND_BATCH/VARS payload."""
    return _batch_payload_parts(_prepare_vars(items))


def _value_from_head(head: dict, raw, copy: bool = True):
    """Value from a var head + its raw bytes (`raw` may be a memoryview
    slice of a larger frame; copy=False returns arrays viewing it)."""
    if head.get("kind") == "selected_rows":
        rows_dt = np.dtype(head["rows_dtype"])
        split = head["n_rows"] * rows_dt.itemsize
        rows = np.frombuffer(raw[:split], dtype=rows_dt)
        data = np.frombuffer(raw[split:], dtype=np.dtype(head["dtype"])) \
            .reshape(head["shape"])
        if copy:
            rows, data = rows.copy(), data.copy()
        return SelectedRows(rows, data, head["height"])
    data = np.frombuffer(raw, dtype=np.dtype(head["dtype"])).reshape(
        head["shape"])
    if copy:
        data = data.copy()
    if head.get("lod") is not None:
        return LoDTensor(data, [tuple(lvl) for lvl in head["lod"]])
    return data


def deserialize_var(payload, copy: bool = True):
    """copy=False skips the defensive `.copy()` for payloads the CALLER
    owns (each frame's payload is a fresh buffer, so the wire paths pass
    False); keep the default for buffers that are reused after the
    call — the returned arrays would silently change under the reader."""
    mv = memoryview(payload)
    (hlen,) = _HDR.unpack_from(mv)
    head = json.loads(bytes(mv[_HDR.size:_HDR.size + hlen]))
    return _value_from_head(head, mv[_HDR.size + hlen:], copy=copy)


def deserialize_batch(payload, copy: bool = False):
    """Batch payload -> [(name, value)].  Default copy=False: values
    slice ONE frame buffer instead of copying per var (the buffer is
    fresh per frame on both ends, so views are safe and keep the whole
    bucket alive only as long as its vars are)."""
    mv = memoryview(payload)
    (hlen,) = _HDR.unpack_from(mv)
    bh = json.loads(bytes(mv[_HDR.size:_HDR.size + hlen]))
    off = _HDR.size + hlen
    out = []
    for h in bh["vars"]:
        n = int(h["nbytes"])
        out.append((h["name"],
                    _value_from_head(h, mv[off:off + n], copy=copy)))
        off += n
    return out


def _read_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly n bytes into ONE preallocated buffer via recv_into
    (the old `bytes += chunk` loop was O(n^2) and re-copied the prefix
    on every chunk)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("peer closed")
        got += r
    return buf


# sendmsg iovec batching: IOV_MAX is 1024 on Linux; stay well under it
_IOV_CHUNK = 64

# names per GET_BATCH frame.  A count cap alone cannot bound the reply
# payload (param sizes are unknown client-side), so the server answers
# ERR "batch too large" for a chunk that would overflow _MAX_PAYLOAD
# and the client re-fetches that chunk per-var.
_GET_BATCH_CHUNK = 256


def _sendall_parts(sock: socket.socket, parts) -> int:
    """Write a list of buffers without concatenating them: scatter-
    gather via sendmsg where available, sequential sendall otherwise.
    Returns total bytes written."""
    views, total = [], 0
    for p in parts:
        v = memoryview(p)
        if v.itemsize != 1:
            v = v.cast("B")
        total += v.nbytes
        if v.nbytes:
            views.append(v)
    if not hasattr(sock, "sendmsg"):
        for v in views:
            sock.sendall(v)
        return total
    i = 0
    while i < len(views):
        sent = sock.sendmsg(views[i:i + _IOV_CHUNK])
        while sent > 0:
            v = views[i]
            if sent >= v.nbytes:
                sent -= v.nbytes
                i += 1
            else:
                views[i] = v[sent:]
                sent = 0
    return total


def _frame_parts(verb: str, name: str = "", payload_parts=(),
                 trace=None) -> list:
    """Scatter-gather frame: [8-byte lengths + head, *payload buffers].
    `trace` is an optional tracing.inject() dict; the field is simply
    absent for untraced senders, so peers predating it (and frames it
    never saw) parse unchanged — wire-compatible both directions."""
    head_d = {"verb": verb, "name": name}
    if trace is not None:
        head_d["trace"] = trace
    head = json.dumps(head_d).encode()
    plen = sum(_blen(p) for p in payload_parts)
    return [_HDR.pack(len(head)) + _HDR.pack(plen) + head,
            *payload_parts]


def _frame_bytes(verb: str, name: str = "", payload: bytes = b"",
                 trace=None) -> bytes:
    return _join_parts(_frame_parts(verb, name, [payload], trace))


def _send_frame(sock: socket.socket, verb: str, name: str = "",
                payload: bytes = b"", trace=None):
    frame = _frame_bytes(verb, name, payload, trace)
    _M_BYTES_SENT.inc(len(frame))
    sock.sendall(frame)


def _send_frame_parts(sock: socket.socket, verb: str, name: str = "",
                      payload_parts=(), trace=None) -> int:
    n = _sendall_parts(sock, _frame_parts(verb, name, payload_parts,
                                          trace))
    _M_BYTES_SENT.inc(n)
    return n


def _bucket_cap(bucket_bytes=None) -> int:
    """Effective SEND bucket size cap: explicit arg, else the
    comm_bucket_bytes flag (PADDLE_TPU_COMM_BUCKET_BYTES)."""
    if bucket_bytes is not None:
        return int(bucket_bytes)
    from ..core.flags import get_flag
    return int(get_flag("comm_bucket_bytes"))


def _recv_frame(sock: socket.socket):
    """-> (verb, name, payload, trace) — `trace` is the propagated trace
    header dict, or None for frames that lack it (older peers)."""
    (hlen,) = _HDR.unpack(_read_exact(sock, 4))
    (plen,) = _HDR.unpack(_read_exact(sock, 4))
    if hlen > _MAX_HEAD or plen > _MAX_PAYLOAD:
        raise ValueError(
            f"frame lengths (head {hlen}, payload {plen}) exceed sanity "
            f"caps ({_MAX_HEAD}, {_MAX_PAYLOAD}): protocol desync or "
            "corrupt frame")
    head = json.loads(_read_exact(sock, hlen))
    payload = _read_exact(sock, plen) if plen else b""
    _M_BYTES_RECV.inc(8 + hlen + plen)
    return head["verb"], head["name"], payload, head.get("trace")


# ---------------------------------------------------------------------------
# server (listen_and_serv_op.cc)
# ---------------------------------------------------------------------------


class VariableServer:
    """Holds a scope; applies the optimize program after `fan_in` barriers.

    Round protocol (listen_and_serv_op.cc:114-175): trainers SEND grad
    vars (stored as `<name>.trainer_<i>` — the per-trainer rename at :82),
    then send BARRIER; once `fan_in` barriers arrive the optimize program
    runs in the server scope and blocked GETs are released.
    """

    def __init__(self, optimize_program, scope, executor, fan_in: int = 1,
                 sync: bool = True, snapshot_dir: Optional[str] = None,
                 snapshot_every: int = 0, enable_batch: bool = True,
                 elastic: bool = False):
        self.program = optimize_program
        self.scope = scope
        self.exe = executor
        self.fan_in = fan_in
        # elastic=True: this server participates in membership-driven
        # rebalancing (cloud/cluster.py).  It holds the FULL optimize
        # program but at each sync round runs only the per-grad slices
        # of grads that actually arrived — ownership is decided by what
        # trainers send per the current cluster view, so parameters can
        # migrate in/out at runtime without rebuilding the program.  The
        # controller drives the FENCE/COMMIT two-phase view change and
        # PUT_BATCH/DROP shard migration verbs.
        self.elastic = elastic
        self._fenced = False
        self._view_epoch = 0
        # enable_batch=False turns off the fused SEND_BATCH/GET_BATCH
        # verbs, making this server answer exactly like one predating
        # them (ERR "unknown verb") — the wire-compat tests pin the
        # batch-capable client's fallback against it
        self.enable_batch = enable_batch
        # per-shard checkpointing (reference go/pserver/service.go:
        # 120-203,346: each pserver snapshots ITS OWN shard with
        # {uuid, md5, timestamp} meta and restores on restart).  Each
        # server gets its OWN snapshot_dir; every `snapshot_every`
        # optimize rounds (sync) / applied updates (async) the shard's
        # persistables are written through io.publish_checkpoint.  On
        # construction an existing valid snapshot is restored into the
        # scope automatically — a replacement pserver claiming the slot
        # resumes the shard where its predecessor died.
        self.snapshot_dir = snapshot_dir
        self.snapshot_every = int(snapshot_every)
        self._updates_since_snapshot = 0
        # sync=False: ASGD — each received grad applies immediately, no
        # barrier round (reference go/pserver SendGrad semantics /
        # legacy --async_pserver; sync barriers become no-ops)
        self.sync = sync
        self._async_progs: Dict[str, object] = {}
        self._async_built = False
        self._async_seen: set = set()
        self._lock = threading.Condition()
        self._barriers = 0
        self._round = 0
        self._trainer_ids: Dict[str, int] = {}
        self._next_trainer = 0
        self._sock: Optional[socket.socket] = None
        self._threads = []
        self._stopping = False
        self.port = None
        if snapshot_dir:
            self.restore_snapshot()
        if (not sync or elastic) and self.program is not None:
            # validate the optimize program HERE, where the user can see
            # the error — a raise inside a handler thread would surface to
            # trainers only as a dropped connection.  Elastic sync mode
            # needs the same per-grad slices: a round must update only
            # the params whose grads arrived (this server's current
            # shard), never the whole program.
            self._build_async_slices()

    # -- lifecycle ----------------------------------------------------------
    def serve(self, port: int = 0) -> int:
        sock = _adopt_prebound(port)
        if sock is not None:
            self._sock = sock
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind(("127.0.0.1", port))
            self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        # fleet telemetry: with PADDLE_TPU_TELEMETRY_REGISTRY set, the
        # first server of this process publishes its /metrics endpoint
        # for the TelemetryCollector (no-op otherwise)
        from ..observability.collector import maybe_announce

        maybe_announce("pserver")
        return self.port

    def register_with(self, registry, kind: str = "pserver",
                      ttl_s: float = 3.0, host: str = "127.0.0.1"):
        """Publish this server in a TTL-lease registry (cloud.registry) so
        trainers discover it and a replacement can claim the slot if this
        process dies (reference go/cmd/pserver/pserver.go:34-45).  Returns
        the live Lease; its `.index` is this pserver's cluster index and
        `.lost` flips if the registry revokes the slot."""
        from ..cloud.registry import Lease

        if self.port is None:
            raise RuntimeError("serve() before register_with()")
        self._lease = Lease(registry, kind, f"{host}:{self.port}", ttl_s)
        return self._lease

    def stop(self):
        self._stopping = True
        lease = getattr(self, "_lease", None)
        if lease is not None and not lease.lost:
            lease.release()
        try:
            if self._sock is not None:
                # shutdown BEFORE close: close() alone may not abort a
                # blocked accept() on every kernel, leaving a grace
                # window where a stopped server accepts (and serves!)
                # one more connection — fatal for crash simulations and
                # wrong for real shutdown
                if not _sched_fault("pserver.accept-stop-race"):
                    try:
                        self._sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                self._sock.close()
        except OSError:
            pass
        with self._lock:
            self._lock.notify_all()

    # -- internals ----------------------------------------------------------
    def _accept_loop(self):
        while not self._stopping:
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return
            if self._stopping and not _sched_fault(
                    "pserver.accept-stop-race"):
                # accept raced stop(): a dead server must not answer.
                # (The _sched_fault toggle reintroduces the pre-PR-7
                # bug for the schedule checker's regression pin —
                # tests/test_concurrency_analysis.py; always False
                # otherwise.)
                try:
                    conn.close()
                except OSError:
                    pass
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _trainer_id(self, peer: str) -> int:
        with self._lock:
            if peer not in self._trainer_ids:
                self._trainer_ids[peer] = self._next_trainer
                self._next_trainer += 1
            return self._trainer_ids[peer]

    def _serve_conn(self, conn: socket.socket):
        peer = None
        try:
            while True:
                try:
                    verb, name, payload, trace = _recv_frame(conn)
                except (ValueError, KeyError, TypeError) as e:
                    # malformed frame (bad lengths / non-JSON head): the
                    # byte stream is desynced, so this CONNECTION is done,
                    # but the server must keep serving everyone else — the
                    # sender reconnects and resends (truncated frames from
                    # a crashed sender land here as ConnectionError via
                    # _read_exact and are equally non-fatal)
                    try:
                        _send_frame(conn, "ERR", f"malformed frame: {e}")
                    except OSError:
                        pass
                    return
                _M_REQUESTS.labels(
                    verb=verb if verb in _KNOWN_VERBS else "other").inc()
                # chaos hook: a delay fault here makes THIS server a
                # straggler — every frame it serves stalls, which the
                # client-side per-endpoint round histogram attributes
                # to this endpoint alone (the straggler drill's lever)
                fault_injector().fire("pserver.serve")
                try:
                    # the handler BUFFERS its reply and sends it only
                    # after the span context manager has exited: the
                    # reply frame is the client's wake-up, so recording
                    # first makes "client saw the reply => the server
                    # span is in the buffer" an invariant.  (Sending
                    # inside the span left a scheduling window where a
                    # loaded host could park this thread between
                    # sendall and the span record while the client — and
                    # a test/collector behind it — already read the
                    # trace: the 1-in-4 wire-propagation flake PRs 11
                    # and 12 logged.)
                    reply = None        # (verb, name, payload_bytes)
                    reply_parts = None  # (verb, name, iovec parts)
                    stop_after = False
                    # the propagated trace context (when the frame has
                    # one) parents this server-side span under the
                    # remote caller's span: one trace id across the wire
                    with obs_tracing.activate(obs_tracing.extract(trace)), \
                            obs_tracing.span(
                                "pserver." + str(verb).lower(),
                                var=name):
                        if verb == "HELLO":
                            peer = name
                            reply = ("OK", "", b"")
                        elif verb == "SEND":
                            tid = self._trainer_id(peer or "anon")
                            with obs_attr.phase("pserver", "recv"):
                                value = deserialize_var(
                                    payload, copy=False)
                                if self.sync:
                                    with self._lock:
                                        # per-trainer grad rename
                                        # (listen_and_serv :82)
                                        self.scope.set_var(
                                            f"{name}.trainer_{tid}",
                                            value)
                                else:
                                    self._apply_async(name, value)
                            reply = ("OK", "", b"")
                        elif verb == "SEND_BATCH" and self.enable_batch:
                            tid = self._trainer_id(peer or "anon")
                            # deserialize the whole bucket OUTSIDE the
                            # lock (views over the frame buffer, no
                            # per-var copies), apply under ONE
                            # acquisition
                            with obs_attr.phase("pserver", "recv"):
                                pairs = deserialize_batch(payload)
                                if self.sync:
                                    with self._lock:
                                        for n, v in pairs:
                                            self.scope.set_var(
                                                f"{n}.trainer_{tid}",
                                                v)
                                else:
                                    self._apply_async_bucket(pairs)
                            reply = ("OK", "", b"")
                        elif verb == "GET_BATCH" and self.enable_batch:
                            names = json.loads(bytes(payload))
                            vals = self._blocking_get_many(names)
                            parts = serialize_batch_parts(
                                list(zip(names, vals)))
                            if sum(_blen(p)
                                   for p in parts) > _MAX_PAYLOAD:
                                # chunking is by NAME count, so huge
                                # params can overflow the frame cap —
                                # tell the client to fetch this chunk
                                # per-var instead of shipping a frame
                                # its parser must reject
                                reply = (
                                    "ERR",
                                    f"batch too large: {len(names)} "
                                    "vars exceed the frame payload cap",
                                    b"")
                            else:
                                reply_parts = ("VARS", "", parts)
                        elif verb == "PUT_BATCH":
                            # shard migration / recovery install: values
                            # land under their CANONICAL names (vs
                            # SEND's per-trainer grad rename) — the
                            # controller and trainer-held recovery both
                            # write params, not grads.  Allowed while
                            # fenced: migration RUNS during the fence.
                            # NOT gated on enable_batch: this is an
                            # elastic verb shipping with FENCE/COMMIT/
                            # DROP, not a PR 5 compat verb — the client
                            # has no per-var fallback for it.
                            pairs = deserialize_batch(payload)
                            with self._lock:
                                for n, v in pairs:
                                    self.scope.set_var(n, v)
                            reply = ("OK", "", b"")
                        elif verb == "DROP":
                            names = json.loads(bytes(payload))
                            # the param, its canonical grad, and stale
                            # per-trainer slots of EITHER must all go —
                            # a migrated-away param's leftover grads
                            # must not feed a later optimize round.
                            # ONE scope pass total (not per name): a
                            # per-trainer slot is `<base>.trainer_<i>`,
                            # so stripping that suffix maps every scope
                            # name onto the doomed set
                            doomed = set()
                            for n in names:
                                doomed.add(n)
                                doomed.add(n + "@GRAD")
                            with self._lock:
                                for sn in list(
                                        self.scope.local_names()):
                                    base = sn
                                    if ".trainer_" in sn:
                                        base = sn.rsplit(
                                            ".trainer_", 1)[0]
                                    if base in doomed:
                                        self.scope.erase(sn)
                            reply = ("OK", "", b"")
                        elif verb == "HAVE":
                            # bootstrap-copy probe: which of these
                            # names does this member hold?  Used by the
                            # controller's initial-placement
                            # consolidation (fenced, read-only)
                            names = json.loads(bytes(payload))
                            with self._lock:
                                held = [n for n in names
                                        if self.scope.has_var(n)]
                            reply = ("OK", "",
                                     json.dumps(held).encode())
                        elif verb == "FENCE":
                            self._apply_fence(int(name))
                            reply = ("OK", "", b"")
                        elif verb == "COMMIT":
                            attrs = (json.loads(bytes(payload))
                                     if payload else {})
                            self._apply_commit(int(name),
                                               attrs.get("fan_in"))
                            reply = ("OK", "", b"")
                        elif verb == "BARRIER":
                            if self.sync:
                                with obs_attr.phase("pserver",
                                                    "barrier"):
                                    self._barrier()
                            reply = ("OK", "", b"")
                        elif verb == "GET":
                            val = self._blocking_get(name)
                            reply_parts = (
                                "VAR", name,
                                _var_payload_parts(
                                    *serialize_var_parts(val)))
                        elif verb == "FLIGHT":
                            # on-demand flight-recorder dump (the ring
                            # of recent spans/events/metric snapshots)
                            reply = ("OK", "", json.dumps(
                                flightrecorder.dump_dict(
                                    reason="wire"),
                                default=str).encode())
                        elif verb == "STOP":
                            reply = ("OK", "", b"")
                            stop_after = True
                        else:
                            reply = ("ERR", f"unknown verb {verb}",
                                     b"")
                    if reply_parts is not None:
                        _send_frame_parts(conn, *reply_parts)
                    elif reply is not None:
                        _send_frame(conn, *reply)
                    if stop_after:
                        self.stop()
                        return
                except (ConnectionError, OSError):
                    raise
                except Exception as e:
                    # a bad REQUEST (undecodable payload, unknown var)
                    # is the client's error to hear about — killing the
                    # connection silently left it hanging in recv
                    _send_frame(conn, "ERR",
                                f"{type(e).__name__}: {e}")
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    # -- per-shard snapshot (go/pserver/service.go:120-203) -----------------
    def _shard_vars(self):
        if self.program is None:
            return {}
        out = {}
        for v in self.program.list_vars():
            if not v.persistable or not self.scope.has_var(v.name):
                continue
            val = self.scope.find_var(v.name)
            if val is None:
                continue
            out[v.name] = np.asarray(val)
        return out

    def snapshot(self, max_keep: int = 3) -> Optional[str]:
        """Write this server's shard (its persistable params +
        accumulators) under snapshot_dir with {uuid, md5, timestamp}
        meta.  Returns the uuid, or None when no snapshot_dir is set."""
        if not self.snapshot_dir:
            return None
        with self._lock:
            data = (self._shard_vars(), self._round)
        return self._write_snapshot(data, max_keep)

    def _write_snapshot(self, data, max_keep: int = 3) -> str:
        """Disk side of snapshot(): runs WITHOUT the lock (npz write +
        md5-of-dir can take seconds on a big shard; trainer handler
        threads must not stall behind it)."""
        import uuid as uuid_mod

        from .. import io as _io

        host, rnd = data
        cp_uuid = uuid_mod.uuid4().hex
        cp_dir = os.path.join(self.snapshot_dir,
                              f"{_io.CHECKPOINT_PREFIX}_{cp_uuid}")
        os.makedirs(cp_dir, exist_ok=True)
        np.savez(os.path.join(cp_dir, "pserver_shard.npz"), **host)
        _io.publish_checkpoint(self.snapshot_dir, cp_uuid, cp_dir,
                               {"round": rnd}, max_keep)
        return cp_uuid

    def restore_snapshot(self):
        """Load the latest valid shard snapshot (if any) into the scope.
        Returns the snapshot meta or None."""
        from .checkpoint import latest_pserver_shard

        data, rnd, meta = latest_pserver_shard(self.snapshot_dir)
        if data is None:
            return None
        for n, v in data.items():
            self.scope.set_var(n, jnp.asarray(v))
        self._round = rnd
        return meta

    def _maybe_snapshot_data(self):
        """Host copies of the shard when a snapshot is due (caller holds
        self._lock); the caller performs the disk write AFTER releasing
        the lock so trainer handler threads never stall behind I/O."""
        if not self.snapshot_dir or self.snapshot_every <= 0:
            return None
        self._updates_since_snapshot += 1
        if self._updates_since_snapshot < self.snapshot_every:
            return None
        self._updates_since_snapshot = 0
        return (self._shard_vars(), self._round)

    def _barrier(self):
        snap = None
        with self._lock:
            # view-change fence: no optimize step may straddle a
            # placement change, so barriers arriving mid-rebalance hold
            # until the controller COMMITs the new view (reads and the
            # migration verbs stay live — the fence only quiesces the
            # round machinery)
            while self._fenced and not self._stopping:
                self._lock.wait(timeout=0.1)
            self._barriers += 1
            if self._barriers >= self.fan_in:
                self._run_optimize()
                self._barriers = 0
                self._round += 1
                snap = self._maybe_snapshot_data()
                self._lock.notify_all()
            else:
                rnd = self._round
                while self._round == rnd and not self._stopping:
                    self._lock.wait(timeout=0.1)
        if snap is not None:
            self._write_snapshot(snap)

    # -- two-phase view change (cloud/cluster.py ClusterController) ---------
    def _apply_fence(self, epoch: int):
        """Phase 1: quiesce the round machinery.  Acquiring the server
        lock waits out any optimize in flight; once set, new BARRIERs
        block until COMMIT, so shard migration runs against frozen
        state and no optimize mixes old and new placements."""
        with self._lock:
            self._fenced = True
            self._view_epoch = max(self._view_epoch, epoch)

    def _apply_commit(self, epoch: int, fan_in=None):
        """Phase 2: adopt the new view.  Updates fan_in to the live
        trainer count, clears per-trainer grad slots (a half-arrived
        round under the OLD placement must not leak into the new
        epoch), and releases every waiter — trainers blocked mid-round
        (e.g. behind a SIGKILLed peer's missing barrier) get their
        BARRIER answered and simply lose that round's update, which
        at-least-once sync SGD tolerates."""
        with self._lock:
            self._view_epoch = max(self._view_epoch, epoch)
            if fan_in:
                self.fan_in = int(fan_in)
            for n in list(self.scope.local_names()):
                if ".trainer_" in n:
                    self.scope.erase(n)
            if self._barriers:
                # release mid-round waiters without an optimize: their
                # grads were just cleared as pre-view state
                self._round += 1
            self._barriers = 0
            self._fenced = False
            self._lock.notify_all()

    def _slice_program(self, keep):
        from ..core.framework import Program

        src = self.program.global_block()
        prog = Program()
        blk = prog.global_block()
        for op_ in keep:
            for v in src.vars.values():
                if not blk.has_var(v.name):
                    blk.create_var(name=v.name, shape=v.shape,
                                   dtype=v.dtype, persistable=True)
            blk.append_op(op_.type, dict(op_.inputs), dict(op_.outputs),
                          dict(op_.attrs))
        return prog

    def _build_async_slices(self):
        """Per-grad program slices (the per-parameter optimizer instance
        of the reference's async pserver, go/pserver/service.go SendGrad)
        plus the EPILOGUE: ops reachable from no gradient (Adam/Adamax
        beta-pow scale ops, global-step increment).  The epilogue runs
        once per full sweep of distinct grads so shared schedule state
        advances at the sync round rate, not once per SEND."""
        src = self.program.global_block()
        grads = {n for op_ in src.ops
                 for n in op_.inputs.get("Grad", [])}
        selected = {}
        claimed = set()
        claimed_by = {}  # id(op) -> first grad slice that claimed it
        for g in sorted(grads):
            keep, produced = [], set()
            for op_ in src.ops:
                ins = {n for ns in op_.inputs.values() for n in ns}
                if g in ins or (produced & ins):
                    prev = claimed_by.setdefault(id(op_), g)
                    if prev != g:
                        # an op reading multiple grads (e.g. a global-norm
                        # clip) would re-execute per arriving grad against
                        # stale peer grads — refuse rather than silently
                        # duplicate; such programs need sync_mode=True
                        raise ValueError(
                            f"async pserver: op {op_.type!r} is reachable "
                            f"from both grad {prev!r} and grad {g!r}; "
                            "multi-grad ops cannot run grads-on-arrival — "
                            "use sync_mode=True for this optimize program")
                    keep.append(op_)
                    claimed.add(id(op_))
                    produced.update(n for ns in op_.outputs.values()
                                    for n in ns)
            selected[g] = self._slice_program(keep)
        epilogue = [op_ for op_ in src.ops if id(op_) not in claimed]
        self._async_progs = selected
        self._async_epilogue = (self._slice_program(epilogue)
                                if epilogue else None)
        self._async_grads = grads
        self._async_built = True

    def _apply_async(self, name, value):
        self._apply_async_bucket([(name, value)])

    def _apply_async_bucket(self, pairs):
        """ASGD application for one or many grads under ONE lock
        acquisition (a SEND_BATCH bucket must not interleave with other
        trainers' grads mid-bucket)."""
        snaps = []
        with self._lock:
            for name, value in pairs:
                snap = self._apply_async_locked(name, value)
                if snap is not None:
                    snaps.append(snap)
        for snap in snaps:
            self._write_snapshot(snap)

    def _apply_async_locked(self, name, value):
        self.scope.set_var(name, value)
        if self.program is None:
            return None
        assert self._async_built  # built (and validated) in __init__
        snap = None
        prog = self._async_progs.get(name)
        if prog is not None:
            self.exe.run(prog, scope=self.scope)
            self._async_seen.add(name)
            snap = self._maybe_snapshot_data()
            if isinstance(value, SelectedRows):
                # applied rows must not survive to the next arrival
                self.scope.erase(name)
        # epilogue fires once per full sweep of DISTINCT grads (Adam
        # beta pows / global step advance at the sync round rate);
        # non-grad sends and resends don't advance the cadence
        if (self._async_epilogue is not None and self._async_grads
                and self._async_seen >= self._async_grads):
            self.exe.run(self._async_epilogue, scope=self.scope)
            self._async_seen.clear()
        return snap

    def _run_optimize(self):
        import time as _time

        t0 = _time.perf_counter()
        with obs_tracing.span("pserver.optimize", round=self._round):
            self._run_optimize_inner()
        dt = _time.perf_counter() - t0
        _M_OPTIMIZE_SECONDS.observe(dt)
        obs_attr.observe_phase("pserver", "optimize", dt)
        # flight ring: the optimize cadence is the first thing a
        # post-mortem of a killed pserver reads (no-op unless armed)
        flightrecorder.note("pserver.optimize", round=self._round,
                            seconds=dt)

    def _run_optimize_inner(self):
        # sum per-trainer grads into the canonical grad var, then run the
        # optimize program (the reference generates sum ops in the pserver
        # program; here the fan-in sum is part of the serving contract).
        # SelectedRows parts merge by row concatenation — duplicate rows
        # are summed by the optimizer's scatter-add, same as the
        # reference's merge_selected_rows.
        names = {}
        for n in list(self.scope.local_names()):
            if ".trainer_" in n:
                base = n.split(".trainer_")[0]
                names.setdefault(base, []).append(n)
        sparse = []
        for base, parts in names.items():
            vals = [self.scope.find_var(p) for p in parts]
            if any(isinstance(v, SelectedRows) for v in vals):
                srs = [v for v in vals if isinstance(v, SelectedRows)]
                if len(srs) != len(vals):
                    # a mixed round would silently drop the dense parts —
                    # heterogeneous trainer programs are a config error
                    raise RuntimeError(
                        f"grad {base!r}: some trainers sent SelectedRows "
                        "and others dense tensors; all trainers must use "
                        "the same is_sparse setting")
                merged = SelectedRows(
                    np.concatenate([np.asarray(s.rows) for s in srs]),
                    np.concatenate([np.asarray(s.value) for s in srs]),
                    srs[0].height)
                self.scope.set_var(base, merged)
                sparse.append((base, parts))
            else:
                vals = [np.asarray(v) for v in vals]
                self.scope.set_var(base, np.sum(vals, axis=0)
                                   if len(vals) > 1 else vals[0])
        if self.program is not None:
            if self.elastic:
                # run only the slices of grads that ARRIVED this round:
                # this server's shard is whatever the current view
                # placed on it, and params migrated away (DROPped) must
                # not be touched by stale program ops
                ran = False
                for base in sorted(names):
                    prog = self._async_progs.get(base)
                    if prog is not None:
                        self.exe.run(prog, scope=self.scope)
                        ran = True
                if ran and self._async_epilogue is not None:
                    # shared schedule state (Adam beta pows, global
                    # step) advances once per optimize round, exactly
                    # like the non-elastic full-program run
                    self.exe.run(self._async_epilogue, scope=self.scope)
            else:
                self.exe.run(self.program, scope=self.scope)
        # per-iteration sparse-row clearing (listen_and_serv_op.cc:171):
        # a round's rows must not be re-applied next round if a slower
        # trainer's SEND hasn't replaced the slot yet
        for base, parts in sparse:
            self.scope.erase(base)
            for p in parts:
                self.scope.erase(p)

    def _blocking_get(self, name: str):
        # The fan-in optimize runs atomically under the server lock, so a
        # GET serializes either fully before or fully after a round's
        # update — and a trainer only GETs after its own barrier returned,
        # i.e. after its round completed.  Reading under the lock is
        # therefore both torn-read-free and deadlock-free (waiting on
        # `_barriers == 0` here could deadlock: a fast trainer's next-round
        # barrier would block a slow trainer's GET forever).
        with self._lock:
            v = self.scope.find_var(name)
        if v is None:
            raise KeyError(f"pserver has no variable {name!r}")
        return v

    def _blocking_get_many(self, names):
        """GET_BATCH read: all names under ONE lock acquisition, so the
        whole bucket reads from the same round's state (a per-name loop
        could straddle an optimize)."""
        with self._lock:
            vals = []
            for n in names:
                # absent names raise KeyError in find_var; declared-
                # but-unset vars come back None — same curated error
                # for both
                v = (self.scope.find_var(n)
                     if self.scope.has_var(n) else None)
                if v is None:
                    raise KeyError(f"pserver has no variable {n!r}")
                vals.append(v)
        return vals


# ---------------------------------------------------------------------------
# client (grpc_client.h AsyncSendVariable/AsyncGetVariable/SendBatchBarrier)
# ---------------------------------------------------------------------------


class BarrierTimeoutError(TimeoutError):
    """A BARRIER response did not arrive within barrier_timeout — in
    sync-SGD fan-in that means some trainer never sent its barrier this
    round, i.e. a lost/wedged trainer (the reference surfaces this as a
    gRPC deadline on SendBatchBarrier)."""


class VariableClient:
    """Trainer-side transport with crash recovery: SEND/GET reconnect and
    resend through a RetryPolicy (both are idempotent — SEND overwrites
    this trainer's grad slot, GET is a read), while BARRIER resends only
    when the write provably never completed (the server counts barrier
    arrivals, so resending after a lost RESPONSE could double-count a
    round) and supports a timeout that detects a lost trainer.

    Note async (sync=False) servers apply a SEND on arrival, so a resent
    grad whose first copy DID land applies twice — inherent to
    at-least-once delivery over ASGD, which is already tolerant of
    reordered/duplicated updates; pass retry_policy=None-like
    max_attempts=1 to forbid it."""

    def __init__(self, endpoint: str, client_id: str = "",
                 connect_timeout: float = 180.0,
                 request_timeout: Optional[float] = None,
                 barrier_timeout: Optional[float] = None,
                 retry_policy: Optional[RetryPolicy] = None):
        import os as _os
        import uuid as _uuid

        self.endpoint = endpoint
        self._host, port = endpoint.rsplit(":", 1)
        self._port = int(port)
        # requests block indefinitely by default: a BARRIER response
        # legitimately waits for straggler trainers + the first
        # optimize-program compile (sync-SGD semantics, like the
        # reference's gRPC client Wait())
        self.request_timeout = request_timeout
        self.barrier_timeout = barrier_timeout
        self.connect_timeout = connect_timeout
        self._policy = retry_policy or RetryPolicy.from_env(
            "PSERVER_RETRY", max_attempts=5, base_delay=0.2,
            max_delay=2.0, deadline=30.0)
        # process-unique id: id(self) can collide ACROSS processes, which
        # would alias two trainers to one per-trainer grad slot.  A
        # reconnect re-HELLOs with the SAME id, so the server keeps
        # routing this trainer to its original grad slot.
        self._cid = client_id or f"{_os.getpid()}-{_uuid.uuid4().hex[:8]}"
        self.sock: Optional[socket.socket] = None
        # None = capability unknown (probe on first batch verb); False =
        # the server answered ERR "unknown verb" once, so every later
        # call goes straight to per-var frames without re-probing
        self._batch_supported: Optional[bool] = None
        # per-instance accounting of serialized PAYLOAD bytes by
        # direction (frame heads excluded, so the two directions are
        # comparable) — comm.CommPool deltas these around a round to
        # feed the round-bytes histogram without double-serializing
        self.bytes_sent = 0
        self.bytes_recv = 0
        self._connect(connect_timeout)

    def _connect(self, connect_timeout: Optional[float] = None):
        import time as _time

        deadline = _time.monotonic() + (connect_timeout
                                        if connect_timeout is not None
                                        else 30.0)
        while True:
            try:
                fault_injector().fire("pserver.connect")
                self.sock = socket.create_connection(
                    (self._host, self._port), timeout=5)
                break
            except OSError:
                # server process may still be booting (jax import +
                # program build); retry until the deadline
                self.sock = None
                if _time.monotonic() >= deadline:
                    raise
                _time.sleep(0.2)
        self.sock.settimeout(None)
        _send_frame(self.sock, "HELLO", self._cid)
        verb, name, _, _ = _recv_frame(self.sock)
        if verb != "OK":
            raise RuntimeError(f"pserver error: {name or verb}")

    def _drop_sock(self):
        s, self.sock = self.sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _request(self, verb: str, name: str = "", payload: bytes = b"",
                 idempotent: bool = True,
                 timeout: Optional[float] = None, payload_parts=None):
        """One framed roundtrip.  Connection-level failures (peer died,
        truncated frame, request timeout) reconnect + resend when
        `idempotent`; protocol-level ERR replies raise RuntimeError
        without retry (retrying a rejected request can't succeed).

        Non-idempotent verbs (BARRIER) still retry failures in the SEND
        phase — an incomplete write provably never reached the server's
        frame parser, so the request was not counted — and only
        propagate failures after the frame was fully handed to the
        kernel, where "applied but response lost" is indistinguishable
        from "never arrived"."""
        timeout = self.request_timeout if timeout is None else timeout
        state = self._policy.begin()
        # the client-side span covers the whole request (reconnects and
        # resends included); its context rides the frame head, so the
        # server-side handling span is its child in the same trace
        with obs_tracing.span("pserver.client." + verb.lower(),
                              endpoint=self.endpoint, var=name):
            trace = obs_tracing.inject()
            return self._request_attempts(state, verb, name, payload,
                                          idempotent, timeout, trace,
                                          payload_parts)

    def _request_attempts(self, state, verb, name, payload, idempotent,
                          timeout, trace, payload_parts=None):
        while True:
            sent = False
            try:
                if self.sock is None:
                    # reconnects cap the boot patience at 30s; clients
                    # built for elastic clusters pass a much smaller
                    # connect_timeout so a dead endpoint fails the
                    # round fast instead of spinning on refusals
                    self._connect(min(self.connect_timeout, 30.0))
                fault_injector().fire("pserver.request")
                self.sock.settimeout(timeout)
                try:
                    if (payload_parts is not None
                            and not fault_injector().rules()):
                        # zero-copy path: the frame never exists as one
                        # contiguous buffer — lengths + head in the
                        # first iovec, value views after it
                        n = _sendall_parts(
                            self.sock,
                            _frame_parts(verb, name, payload_parts,
                                         trace))
                        _M_BYTES_SENT.inc(n)
                        payload_n = sum(_blen(p)
                                        for p in payload_parts)
                    else:
                        # chaos rules mangle whole frames, so join the
                        # parts when the injector is armed
                        if payload_parts is not None:
                            payload = _join_parts(payload_parts)
                        frame = _frame_bytes(verb, name, payload, trace)
                        data = fault_injector().mangle(
                            "pserver.send", frame)
                        _M_BYTES_SENT.inc(len(data))
                        self.sock.sendall(data)
                        payload_n = len(payload)
                        if data != frame:
                            # injected mid-write crash / wire
                            # corruption: the server got a mangled
                            # frame; fail our side like the sender
                            # process died
                            raise ConnectionError(
                                "fault injection: mangled frame")
                    sent = True
                    rverb, rname, rpayload, _ = _recv_frame(self.sock)
                    # account the COMPLETED roundtrip only — a counted
                    # failed attempt would break sent/recv symmetry
                    self.bytes_sent += payload_n
                    self.bytes_recv += len(rpayload)
                finally:
                    if self.sock is not None:
                        self.sock.settimeout(None)
                if rverb == "ERR":
                    if rname.startswith("malformed frame"):
                        # the server is closing this desynced connection;
                        # for idempotent requests a fresh connection +
                        # resend is the recovery path
                        raise ConnectionError(
                            f"pserver rejected frame: {rname}")
                    raise RuntimeError(f"pserver error: {rname}")
                return rverb, rname, rpayload
            except (ConnectionError, OSError,  # incl. timeouts
                    ValueError, KeyError, TypeError) as e:
                # the Value/Key/TypeError arm is a malformed RESPONSE
                # (corrupt lengths / non-JSON head): the stream is
                # desynced, so the socket must be dropped either way —
                # reusing it would parse garbage as the next frame header
                timed_out = isinstance(e, (socket.timeout, TimeoutError))
                self._drop_sock()
                if not idempotent and sent:
                    raise
                state.record(e, what=(f"pserver {self.endpoint}: "
                                      f"{verb} {name}".rstrip()))
                if timed_out and timeout is not None:
                    # the deadline already consumed the patience budget
                    state._next_delay = 0.0
                state.sleep()

    def send_var(self, name: str, value):
        head, parts = serialize_var_parts(value)
        rverb, _, _ = self._request(
            "SEND", name, payload_parts=_var_payload_parts(head, parts))
        if rverb != "OK":
            raise RuntimeError(f"pserver error sending {name!r}: {rverb}")

    # -- fused transfers (SEND_BATCH/GET_BATCH with legacy fallback) --------
    def send_vars(self, items, bucket_bytes: Optional[int] = None):
        """Fused SEND: pack `[(name, value)]` into arrival-order buckets
        capped at `bucket_bytes` (default: the comm_bucket_bytes flag /
        PADDLE_TPU_COMM_BUCKET_BYTES) and ship each bucket as ONE
        SEND_BATCH frame.  Falls back to per-var legacy SENDs against a
        server that answers ERR (wire compat both ways) or when
        bucketing is disabled (cap <= 0)."""
        items = list(items)
        cap = _bucket_cap(bucket_bytes)
        if cap <= 0 or self._batch_supported is False or len(items) <= 1:
            for n, v in items:
                self.send_var(n, v)
            return
        prepared = _prepare_vars(items)
        buckets = _pack_buckets(prepared, cap)
        for bi, bucket in enumerate(buckets):
            if not self._send_bucket(bucket, cap):
                # legacy server: this and every later bucket per-var
                for later in buckets[bi:]:
                    for n, head, parts, _ in later:
                        rverb, _, _ = self._request(
                            "SEND", n,
                            payload_parts=_var_payload_parts(head,
                                                             parts))
                        if rverb != "OK":
                            raise RuntimeError(
                                f"pserver error sending {n!r}: {rverb}")
                return

    def _send_bucket(self, bucket, cap: int) -> bool:
        """One SEND_BATCH frame; False (nothing sent) when the server
        does not speak batch."""
        if self._batch_supported is False:
            return False
        try:
            rverb, _, _ = self._request(
                "SEND_BATCH", "",
                payload_parts=_batch_payload_parts(bucket))
        except RuntimeError as e:
            if "unknown verb" in str(e):
                self._batch_supported = False
                _M_BATCH_FALLBACK.inc()
                return False
            raise
        if rverb != "OK":
            raise RuntimeError(f"pserver error on SEND_BATCH: {rverb}")
        self._batch_supported = True
        _M_BUCKET_VARS.observe(len(bucket))
        _M_BUCKET_FILL.observe(sum(it[3] for it in bucket) / cap)
        return True

    def get_vars(self, names, bucket_bytes: Optional[int] = None):
        """Fused GET: one GET_BATCH frame per `_GET_BATCH_CHUNK` names
        (the reply slices a single buffer — no per-var copies); per-var
        GETs against a legacy server, or whenever fusion is disabled
        (cap <= 0 — the same switch send_vars honors, so
        comm_bucket_bytes=0 really is the whole legacy wire path).
        Returns values in `names` order."""
        names = list(names)
        fused = _bucket_cap(bucket_bytes) > 0
        out = []
        i = 0
        while i < len(names):
            if (not fused or self._batch_supported is False
                    or len(names) - i == 1):
                out.append(self.get_var(names[i]))
                i += 1
                continue
            chunk = names[i:i + _GET_BATCH_CHUNK]
            try:
                rverb, _, rpayload = self._request(
                    "GET_BATCH", "", json.dumps(chunk).encode())
            except RuntimeError as e:
                msg = str(e)
                if "unknown verb" in msg:
                    self._batch_supported = False
                    _M_BATCH_FALLBACK.inc()
                    continue  # redo this chunk per-var
                if "batch too large" in msg:
                    # this chunk's params overflow one reply frame —
                    # per-var GETs for IT only; the endpoint still
                    # speaks batch
                    out.extend(self.get_var(n) for n in chunk)
                    i += len(chunk)
                    continue
                raise
            if rverb != "VARS":
                raise RuntimeError(
                    f"pserver error on GET_BATCH: {rverb}")
            pairs = deserialize_batch(rpayload)
            got = [n for n, _ in pairs]
            if got != chunk:
                raise RuntimeError(
                    f"GET_BATCH answered vars {got[:3]}... for request "
                    f"{chunk[:3]}...: protocol desync")
            self._batch_supported = True
            out.extend(v for _, v in pairs)
            i += len(chunk)
        return out

    # -- elastic cluster verbs (cloud/cluster.py view changes) --------------
    def put_vars(self, items, bucket_bytes: Optional[int] = None) -> int:
        """Install values under their CANONICAL names (shard migration /
        trainer-held recovery — NOT grads: SEND's per-trainer rename is
        deliberately bypassed).  Buckets like send_vars; returns payload
        bytes shipped.  Elastic servers always speak PUT_BATCH (the verb
        ships with FENCE/COMMIT), so there is no legacy fallback."""
        prepared = _prepare_vars(list(items))
        cap = _bucket_cap(bucket_bytes)
        if cap <= 0:
            cap = 1 << 62  # bucketing off: one bucket, still PUT_BATCH
        buckets = _pack_buckets(prepared, cap)
        total = 0
        for bucket in buckets:
            rverb, _, _ = self._request(
                "PUT_BATCH", "", payload_parts=_batch_payload_parts(bucket))
            if rverb != "OK":
                raise RuntimeError(f"pserver error on PUT_BATCH: {rverb}")
            total += sum(it[3] for it in bucket)
        return total

    def drop_vars(self, names):
        """Erase vars (and their per-trainer grad slots) migrated away
        from this server by a rebalance."""
        rverb, _, _ = self._request("DROP", "",
                                    json.dumps(list(names)).encode())
        if rverb != "OK":
            raise RuntimeError(f"pserver error on DROP: {rverb}")

    def have_vars(self, names):
        """The subset of `names` this server currently holds — the
        controller's bootstrap-copy probe before initial placement."""
        rverb, _, rpayload = self._request(
            "HAVE", "", json.dumps(list(names)).encode())
        if rverb != "OK":
            raise RuntimeError(f"pserver error on HAVE: {rverb}")
        return set(json.loads(bytes(rpayload)))

    def fence(self, epoch: int):
        """Two-phase view change, phase 1: quiesce rounds (idempotent —
        re-fencing an already-fenced server just renews the epoch)."""
        rverb, _, _ = self._request("FENCE", str(int(epoch)))
        if rverb != "OK":
            raise RuntimeError(f"pserver error on FENCE: {rverb}")

    def commit(self, epoch: int, fan_in: Optional[int] = None):
        """Two-phase view change, phase 2: adopt the view (new fan_in,
        cleared pre-view grad slots, fence released)."""
        payload = json.dumps({"fan_in": fan_in}).encode()
        rverb, _, _ = self._request("COMMIT", str(int(epoch)), payload)
        if rverb != "OK":
            raise RuntimeError(f"pserver error on COMMIT: {rverb}")

    def send_batch_barrier(self, timeout: Optional[float] = None):
        """Sync-round barrier.  `timeout` (or the instance-level
        barrier_timeout) bounds the wait; expiry raises
        BarrierTimeoutError — the sync-SGD signature of a trainer that
        died before barriering this round."""
        import time as _time

        timeout = self.barrier_timeout if timeout is None else timeout
        t0 = _time.perf_counter()
        try:
            rverb, _, _ = self._request("BARRIER", idempotent=False,
                                        timeout=timeout)
            _M_BARRIER_WAIT.observe(_time.perf_counter() - t0)
        except (socket.timeout, TimeoutError) as e:
            raise BarrierTimeoutError(
                f"pserver {self.endpoint}: no barrier release within "
                f"{timeout}s — a trainer in this round is lost or "
                "wedged") from e
        if rverb != "OK":
            raise RuntimeError(f"pserver error at barrier: {rverb}")

    def get_var(self, name: str):
        rverb, _, rpayload = self._request("GET", name)
        if rverb != "VAR":
            raise RuntimeError(f"pserver error fetching {name!r}: {rverb}")
        # the reply buffer is this frame's alone — a view is safe
        return deserialize_var(rpayload, copy=False)

    def get_flight_record(self) -> dict:
        """On-demand flight-recorder dump of the SERVER process
        (observability/flightrecorder.py): its ring of recent spans,
        structured events and metric snapshots.  Works against any
        live server; one that never armed a recorder answers an honest
        empty ring (``armed: false``)."""
        rverb, rname, rpayload = self._request("FLIGHT")
        if rverb != "OK":
            raise RuntimeError(
                f"pserver error on FLIGHT: {rname or rverb}")
        return json.loads(bytes(rpayload))

    def stop_server(self):
        rverb, _, _ = self._request("STOP", idempotent=False)
        if rverb != "OK":
            raise RuntimeError(f"pserver error on stop: {rverb}")

    def close(self):
        self._drop_sock()

    def __del__(self):
        try:
            self._drop_sock()
        except Exception:
            pass
