"""PipelineExecutor: run a `fluid.Program` under dp x pp pipeline
parallelism.

This closes the gap between the Program DSL and parallel/pipeline.py's
GPipe schedule: the reference made per-layer device placement reachable
from user config (ParallelNeuralNetwork,
/root/reference/paddle/gserver/gradientmachines/ParallelNeuralNetwork.h
+ .cpp, layer `deviceId`, flag `parallel_nn`
/root/reference/paddle/utils/Flags.cpp:37); here the user annotates the
Program's repeated trunk with `fluid.pipeline_stage(i)` and this executor
runs it as one jitted SPMD program:

  * forward ops before the first staged op ("pre", e.g. embedding) and
    after the last staged op ("post", e.g. classifier + loss) run on the
    FULL batch, dp-sharded, exactly as the serial interpreter would run
    them (same op lowerings, same per-op PRNG derivation);
  * the staged trunk is validated to be structurally homogeneous (same op
    sequence per stage), its per-stage parameters are stacked on a
    leading [pp] axis, and it executes through `spmd_pipeline`
    (shard_map + ppermute + lax.scan) on microbatched activations;
  * gradients come from `jax.value_and_grad` of that composed forward —
    autodiff derives the reverse pipeline schedule — and the Program's
    OWN optimizer ops then apply the update: stage-0's optimizer op runs
    once per parameter group on the stacked arrays (elementwise updates
    are stage-invariant; attrs are validated identical across stages),
    outer parameters run their op individually.

Tensor and sequence parallelism compose in the SAME program the
TPU-native way:

  * `tp_axis='tp'` Megatron-splits every staged weight by the
    alternation rule (see `_derive_tp_specs`) and leaves the tp axis in
    GSPMD-auto mode inside the pipeline's shard_map
    (spmd_pipeline auto_axes) — op lowerings keep seeing global shapes
    and XLA's sharding propagation inserts the tp psum after
    row-parallel matmuls.  No lowering knows tp exists.
  * `sp_axis='sp'` shards the trunk activations' sequence dim; the
    flash_attention lowering detects the manual sp axis on its
    ExecContext and runs ring attention (parallel/ring_attention.py
    ring_attention_local) — K/V blocks rotate over ICI while every
    other trunk op runs on its local sequence block unchanged.

So one `fluid.layers` Program trains under dp x pp x tp (x sp) with the
Program's own optimizer ops — the full composition the reference needed
three subsystems for (MultiGradientMachine x ParallelNeuralNetwork x
sharded pservers).

Stochastic and stateful ops in the trunk (the reference accepted ANY
layer under per-layer placement — dropout and batch-norm included):

  * dropout IS supported: masks are batch-position-keyed (each row's
    mask depends only on the op key and the row's GLOBAL batch index,
    ops/activation.py) and the stage body substitutes each stage's
    SERIAL op identity into the key derivation (stage_tags +
    ExecContext.tag_lookup), so a pipelined transformer with dropout
    reproduces the serial run bit-for-bit — pinned in
    tests/test_pipeline.py.  Under sp, each rank additionally folds its
    seq-block index (independent, distribution-equivalent to serial).
  * batch-norm stays OUT of the staged trunk by design: its running
    stats are persistable writes, and a cross-microbatch running mean
    inside one scanned schedule would make stage output depend on
    schedule order — the very nondeterminism BN's own batch statistics
    already cause across dp.  The supported placements: BN in pre/post
    (full-batch semantics, aux-state carried), or stateless
    normalization (layer_norm) in the trunk — which is also the
    transformer convention.  Other stochastic ops error with guidance.

Constraints (validated with explicit errors): stages must be
structurally identical with a single activation in/out of fixed shape
(the usual GPipe decomposition — embedding/classifier live outside the
trunk); stage count must equal the 'pp' mesh axis; trunk stages must be
stateless (no persistable writes); grad-transform ops (clip/regularizer)
are supported for outer params but not for staged params.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing

from ..core.execution import DictEnv, ExecContext, run_op
from ..core.framework import (GRAD_SUFFIX, Parameter, Variable,
                              default_startup_program, grad_var_name)
from ..core.executor import CPUPlace, Executor
from ..core.scope import Scope
from .checkpoint import ShardedCheckpointMixin
from .executor import _trace_flags
from .mesh import count_collectives, make_mesh
from .pipeline import microbatch, spmd_pipeline, unmicrobatch

__all__ = ["PipelineExecutor"]


def _attr_sig(attrs: Dict) -> tuple:
    """Hashable attr signature (pipeline_stage excluded) for comparing
    ops across stages."""
    def enc(v):
        if isinstance(v, np.ndarray):
            return ("nd", v.shape, str(v.dtype), v.tobytes())
        if isinstance(v, (list, tuple)):
            return tuple(enc(x) for x in v)
        if isinstance(v, dict):
            return tuple(sorted((k, enc(x)) for k, x in v.items()))
        return v
    return tuple(sorted((k, enc(v)) for k, v in attrs.items()
                        if k != "pipeline_stage"))


class PipelineExecutor(ShardedCheckpointMixin):
    def __init__(
        self,
        program,
        feed_names: Sequence[str],
        fetch_list: Sequence,
        mesh,
        startup_program=None,
        n_micro: int = 4,
        batch_axis: str = "dp",
        stage_axis: str = "pp",
        tp_axis: Optional[str] = None,
        sp_axis: Optional[str] = None,
        param_shardings: Optional[Dict[str, P]] = None,
        shard_optimizer_states: bool = False,
        schedule: str = "gpipe",
        seed: int = 0,
    ):
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule must be 'gpipe' or '1f1b', got {schedule!r}")
        self.schedule = schedule
        if isinstance(mesh, dict):
            mesh = make_mesh(mesh)
        self.mesh: Mesh = mesh
        self.batch_axis = batch_axis
        self.stage_axis = stage_axis
        for ax, what in ((tp_axis, "tp_axis"), (sp_axis, "sp_axis")):
            if ax is not None and ax not in mesh.shape:
                raise ValueError(f"{what}={ax!r} is not a mesh axis "
                                 f"(mesh has {tuple(mesh.shape)})")
        self.tp_axis = tp_axis if (tp_axis
                                   and mesh.shape[tp_axis] > 1) else None
        self.sp_axis = sp_axis if (sp_axis
                                   and mesh.shape[sp_axis] > 1) else None
        self._param_shardings = dict(param_shardings or {})
        self.n_micro = int(n_micro)
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = [
            v.name if isinstance(v, Variable) else str(v)
            for v in fetch_list
        ]
        self._seed = seed
        self._step = 0

        # PADDLE_TPU_VERIFY pre-flight, gated inside preflight
        # (distributed-lint checks the pipeline_stage annotations this
        # executor is about to trust)
        from ..analysis import preflight

        preflight(program, feed_names=self.feed_names,
                  fetch_names=self.fetch_names)
        block = program.global_block()
        self._persistable = {v.name for v in program.list_vars()
                             if v.persistable}
        self._partition(block)
        self.tp_param_specs = (self._derive_tp_specs(block)
                               if self.tp_axis else {})
        if self.sp_axis:
            shp = tuple(block.var(self._trunk_in).shape or ())
            if (len(shp) >= 2 and shp[1] > 0
                    and shp[1] % self.mesh.shape[self.sp_axis]):
                raise ValueError(
                    f"trunk activation {self._trunk_in!r} sequence dim "
                    f"{shp[1]} does not divide the '{self.sp_axis}' axis "
                    f"({self.mesh.shape[self.sp_axis]})")
            if any(op.type == "softmax" for op in self._stage_ops[0]):
                raise NotImplementedError(
                    "the staged trunk contains a softmax op — composed "
                    "(score-materializing) attention computes over the "
                    "LOCAL sequence block under sequence parallelism "
                    "and would silently truncate the context; use the "
                    "flash_attention path (no attention-weight dropout) "
                    "in an sp trunk")
        self._plan_update(block)
        if self.schedule == "1f1b":
            self._validate_1f1b(block)

        # --- host-side init, then stack + place -------------------------
        startup = startup_program or default_startup_program()
        scope = Scope()
        Executor(CPUPlace()).run(startup, scope=scope)
        self._init_states(scope, shard_optimizer_states)

        self._jit_step = self._make_jit_step()
        self._trace_flags_state = _trace_flags()

    # ------------------------------------------------------------------
    # program partitioning
    # ------------------------------------------------------------------
    def _partition(self, block):
        pp = self.mesh.shape[self.stage_axis]
        ops = block.ops
        bwd_start = None
        for i, op in enumerate(ops):
            outs = op.output_names()
            if (op.type == "fill_constant" and len(outs) == 1
                    and outs[0].endswith(GRAD_SUFFIX)):
                bwd_start = i
                break
        if bwd_start is None:
            raise ValueError(
                "PipelineExecutor needs a training program: call "
                "optimizer.minimize(loss) before constructing it")
        self._bwd_start = bwd_start
        self._loss_name = ops[bwd_start].output_names()[0][
            : -len(GRAD_SUFFIX)]

        pre, post = [], []
        stages: Dict[int, list] = {}
        self._trunk_has_random = False
        mode = "pre"
        for op in ops[:bwd_start]:
            s = op.attrs.get("pipeline_stage")
            if s is None:
                if mode == "pre":
                    pre.append(op)
                else:
                    mode = "post"
                    post.append(op)
            else:
                if mode == "post":
                    raise ValueError(
                        f"op {op.type} tagged pipeline_stage={s} appears "
                        "after unstaged post-trunk ops — the staged trunk "
                        "must be contiguous")
                mode = "stage"
                stages.setdefault(int(s), []).append(op)
        if not stages:
            raise ValueError(
                "no ops tagged with fluid.pipeline_stage(i) — annotate "
                "the repeated trunk blocks to pipeline this program")
        idxs = sorted(stages)
        if idxs != list(range(len(idxs))):
            raise ValueError(f"stage indices must be 0..S-1, got {idxs}")
        if len(idxs) != pp:
            raise ValueError(
                f"{len(idxs)} pipeline stages but mesh axis "
                f"'{self.stage_axis}' has {pp} devices — they must match "
                "(fold several layers into one stage to reduce the count)")
        self._pre_ops, self._post_ops = pre, post
        self._stage_ops = [stages[i] for i in idxs]
        self._validate_stages(block)

        # persistable writes by pre/post (BN stats, counters) are carried
        # as rw aux state; staged ops must be stateless
        self._aux_writes = sorted({
            n for op in pre + post for n in op.output_names()
            if n in self._persistable})
        from ..core import registry as op_registry
        for s, sops in enumerate(self._stage_ops):
            bad = [n for op in sops for n in op.output_names()
                   if n in self._persistable]
            if bad:
                raise NotImplementedError(
                    f"stage {s} writes persistable var(s) {bad}: staged "
                    "trunk ops must be stateless (keep BN/counters in the "
                    "pre/post sections)")
            for op in sops:
                try:
                    info = op_registry.get_op_info(op.type)
                except KeyError:
                    continue
                if info.random and not op.attrs.get("is_test", False):
                    if op.type == "dropout":
                        # supported: batch-position-keyed masks + per-
                        # stage serial op tags make the pipelined draw
                        # bit-identical to serial (see _make_jit_step)
                        self._trunk_has_random = True
                        continue
                    raise NotImplementedError(
                        f"stage {s} contains stochastic op {op.type!r}: "
                        "only dropout has the batch-position-keyed "
                        "derivation that keeps one traced stage body "
                        "consistent with serial execution — run other "
                        "stochastic ops in the pre/post sections (or "
                        "set is_test)")

    def _stage_io(self, ops, block):
        """(ordered external activation reads, ordered Parameter reads,
        set of names written) for one stage's op list."""
        written, ext, params = set(), [], []
        for op in ops:
            for n in op.input_names():
                if not n or n in written or n in ext or n in params:
                    continue
                if n in self._persistable:
                    v = block.var(n)
                    if not isinstance(v, Parameter):
                        raise NotImplementedError(
                            f"stage op {op.type} reads persistable "
                            f"non-parameter {n!r}: staged trunks may only "
                            "read activations and their own parameters")
                    params.append(n)
                else:
                    ext.append(n)
            written.update(op.output_names())
        return ext, params, written

    def _validate_stages(self, block):
        pp = len(self._stage_ops)
        sigs, ios = [], []
        for sops in self._stage_ops:
            sigs.append([
                (op.type, _attr_sig(op.attrs),
                 tuple(sorted((k, len(v)) for k, v in op.inputs.items())),
                 tuple(sorted((k, len(v)) for k, v in op.outputs.items())))
                for op in sops])
            ios.append(self._stage_io(sops, block))
        for s in range(1, pp):
            if sigs[s] != sigs[0]:
                raise ValueError(
                    f"pipeline stage {s} is not structurally identical to "
                    "stage 0 (op sequence/attrs differ) — spmd_pipeline "
                    "runs ONE traced stage body with per-stage parameters, "
                    "so every stage must build the same layer stack")
        self._stage_params: List[List[str]] = [io[1] for io in ios]
        for s in range(1, pp):
            if len(self._stage_params[s]) != len(self._stage_params[0]):
                raise ValueError("per-stage parameter counts differ")
            for a, b in zip(self._stage_params[0], self._stage_params[s]):
                va, vb = block.var(a), block.var(b)
                if tuple(va.shape or ()) != tuple(vb.shape or ()):
                    raise ValueError(
                        f"stage param shape mismatch: {a} {va.shape} vs "
                        f"{b} {vb.shape}")

        # activation plumbing: one in, one out, chained stage to stage
        consumed_later: Dict[int, set] = {}
        later = {n for op in self._post_ops for n in op.input_names()}
        later |= set(self.fetch_names)
        for s in reversed(range(pp)):
            consumed_later[s] = set(later)
            later |= {n for op in self._stage_ops[s]
                      for n in op.input_names()}
        self._trunk_in = None
        self._stage_out: List[str] = []
        prev_out = None
        for s in range(pp):
            ext, _, written = ios[s]
            if len(ext) != 1:
                raise ValueError(
                    f"stage {s} reads {len(ext)} external activations "
                    f"({ext}): exactly one [batch, ...] activation may "
                    "cross a stage boundary")
            outs = sorted(written & consumed_later[s])
            if len(outs) != 1:
                raise ValueError(
                    f"stage {s} emits {len(outs)} activations consumed "
                    f"downstream ({outs}): exactly one may cross the "
                    "boundary")
            if s == 0:
                self._trunk_in = ext[0]
            elif ext[0] != prev_out:
                raise ValueError(
                    f"stage {s} input {ext[0]!r} is not stage {s-1}'s "
                    f"output {prev_out!r}")
            prev_out = outs[0]
            self._stage_out.append(prev_out)
        # the traced stage body (stage 0's ops) emits stage 0's boundary
        # name; the post section consumes the LAST stage's name
        self._trunk_out = self._stage_out[-1]

    # ------------------------------------------------------------------
    # 1F1B section analysis
    # ------------------------------------------------------------------
    def _validate_1f1b(self, block):
        """Under the 1F1B schedule the POST section (classifier + loss)
        runs per microbatch on the LAST stage, inside the schedule scan
        (spmd_pipeline_1f1b last_fn), so the backward wave can start
        while later microbatches are still in flight.  That imposes two
        structural requirements checked here: the post section may not
        write persistables (its per-microbatch execution would apply
        stateful updates n_micro times, e.g. BN stats), and any
        pre-section float activation consumed by post would need its
        gradient routed around the pipeline (not supported — keep such
        paths wholly in pre or post).  It also assumes the Program's
        loss is a batch MEAN (the book convention): per-microbatch
        losses are combined as sum/ (n_micro * dp [* sp]), which equals
        the serial value exactly for mean losses — pinned by the
        serial-equality tests."""
        post_reads = {n for op in self._post_ops for n in
                      op.input_names()}
        post_writes = {n for op in self._post_ops for n in
                       op.output_names()}
        post_aux = sorted(post_writes & set(self._persistable))
        if post_aux:
            raise NotImplementedError(
                f"schedule='1f1b': post section writes persistable "
                f"var(s) {post_aux} — per-microbatch post execution "
                "would apply them n_micro times (keep BN/counters in "
                "pre, or use schedule='gpipe')")
        pre_written = {n for op in self._pre_ops for n in
                       op.output_names()}
        side = sorted(
            n for n in post_reads
            if n in pre_written and n not in self._persistable
            and n != self._trunk_out and n)
        self._side_vars = side
        bad = [n for n in side
               if str(block.var(n).dtype).startswith(("float",
                                                      "bfloat"))]
        if bad:
            raise NotImplementedError(
                f"schedule='1f1b': float pre-section output(s) {bad} "
                "are consumed by the post section — their gradient "
                "would bypass the pipeline (not supported; use "
                "schedule='gpipe' or restructure)")
        if self.sp_axis:
            # the per-microbatch post section sees a sequence-sharded
            # trunk output, so EVERY y-stream leaf (post-read feeds AND
            # pre-produced side vars) must carry the same seq dim at
            # position 1 to shard alongside it.  The check is
            # positional and by-size (the [B, S, ...] batch-major
            # convention) — a non-sequence dim that coincidentally
            # equals S would pass; the serial-equality tests are the
            # backstop for such programs.  The combination also
            # assumes the post section is SEQ-LOCAL up to the final
            # batch-mean (true of the reshape + softmax_xent + mean
            # shape; a post op reducing ACROSS positions would compute
            # per-shard reductions — covered by the same tests).
            out_shape = tuple(block.var(self._trunk_out).shape or ())
            seq = out_shape[1] if len(out_shape) > 1 else None
            y_like = ([n for n in self.feed_names if n in post_reads]
                      + side)
            bad = []
            for n in y_like:
                shp = tuple(block.var(n).shape or ())
                if len(shp) < 2 or shp[1] != seq:
                    bad.append((n, shp))
            if bad:
                raise NotImplementedError(
                    f"schedule='1f1b' with sp_axis: post-section "
                    f"input(s) {bad} lack the trunk output's sequence "
                    f"dim {seq} at position 1, so they cannot shard "
                    "with the sequence-parallel trunk output — use "
                    "schedule='gpipe' (post on the gathered full batch)")

    # ------------------------------------------------------------------
    # tensor-parallel spec derivation (Megatron alternation)
    # ------------------------------------------------------------------
    def _derive_tp_specs(self, block) -> Dict[str, P]:
        """Walk stage 0's ops and assign each staged parameter a
        tensor-parallel PartitionSpec (WITHOUT the leading pp dim) by the
        Megatron alternation rule: a matmul consuming a feature-replicated
        activation splits its weight column-wise (output features over
        tp, activation becomes feature-sharded); a matmul consuming a
        feature-sharded activation splits row-wise (contraction over tp —
        XLA's sharding propagation inserts the psum — and the activation
        returns to replicated).  Biases follow their activation; LN
        params stay replicated (full-feature op on the replicated
        residual stream).  This reproduces Megatron's column->row split
        for attention (wq/wk/wv col, wo row) and FFN (w1 col, w2 row) on
        the DSL transformer block, and degrades to alternating col/row
        on a plain fc trunk.

        The specs are APPLIED purely as NamedShardings on the stacked
        arrays: the stage body runs under shard_map with the tp axis in
        GSPMD-auto mode (spmd_pipeline auto_axes), so op lowerings keep
        seeing global shapes and the compiler places the collectives —
        no manual psum in any lowering.  Reference capability:
        /root/reference/paddle/gserver/gradientmachines/
        ParallelNeuralNetwork.h (per-layer placement); the composition
        itself is beyond-reference (SURVEY.md §2.5)."""
        tp = self.tp_axis
        specs: Dict[str, P] = {}
        tagged = set()  # activations whose feature dim is tp-sharded
        param0 = set(self._stage_params[0])
        for op in self._stage_ops[0]:
            outs = op.output_names()
            if op.type == "mul":
                x = op.inputs["X"][0]
                y = op.inputs["Y"][0]
                if y in param0:
                    if y in specs:
                        raise NotImplementedError(
                            f"staged param {y!r} is read by two matmuls "
                            "— tp auto-split needs a single role per "
                            "weight (pass tp_axis=None or restructure)")
                    if x in tagged:
                        specs[y] = P(tp, None)      # row-parallel
                    else:
                        specs[y] = P(None, tp)      # column-parallel
                        tagged.update(outs)
                    continue
            elif op.type == "elementwise_add":
                x = op.inputs.get("X", [None])[0]
                y = op.inputs.get("Y", [None])[0]
                if y in param0:                     # bias
                    new = P(tp) if x in tagged else P()
                    if y in specs and specs[y] != new:
                        raise NotImplementedError(
                            f"staged bias {y!r} is consumed by adds with "
                            "different feature shardings — tp auto-split "
                            "needs a single role per param (pass "
                            "tp_axis=None or restructure)")
                    specs[y] = new
                    if x in tagged:
                        tagged.update(outs)
                    continue
            elif op.type == "layer_norm":
                # full-feature op on the replicated stream: params (and
                # output) replicated.  A tp-sharded input here would make
                # GSPMD all-gather — correct but wasteful; the pre-LN
                # trunk never produces one.
                continue
            # default: feature sharding propagates through elementwise /
            # reshape / transpose / attention ops
            if any(n in tagged for n in op.input_names()):
                tagged.update(outs)
        return specs

    # ------------------------------------------------------------------
    # update planning (the Program's own optimizer ops)
    # ------------------------------------------------------------------
    def _plan_update(self, block):
        ops = block.ops
        start = self._bwd_start
        stage0 = set(self._stage_params[0])
        stage_rest = {n for sp in self._stage_params[1:] for n in sp}
        # values the update phase can bind: every persistable EXCEPT
        # stage params of stages >= 1 (stored stacked under stage-0
        # names), plus the jax.grad cotangents under canonical names
        bindable = set(self._persistable) - stage_rest
        self._trainable = [p.name for p in block.all_parameters()
                           if p.trainable]
        grad_names = {grad_var_name(n) for n in self._trainable
                      if n not in stage_rest}
        bindable |= grad_names

        plan = []
        produced = set(bindable)
        self._group_opt_ops: Dict[str, object] = {}
        for op in ops[start:]:
            is_opt = "Param" in op.inputs and "ParamOut" in op.outputs
            pname = op.inputs["Param"][0] if is_opt else None
            if is_opt and pname in stage_rest:
                # covered by the stacked run of stage-0's op; validate
                plan.append(("skip_stage_opt", op))
                continue
            runnable = all((not n) or n in produced
                           for n in op.input_names())
            if runnable:
                plan.append(("run", op))
                produced.update(op.output_names())
                if is_opt and pname in stage0:
                    self._group_opt_ops[pname] = op
            else:
                # backward/grad-computation op: replaced by jax.grad
                # (empty/@EMPTY@ slots are pruned-grad placeholders)
                tainted_outs = [n for n in op.output_names()
                                if GRAD_SUFFIX in n
                                or n in ("", "@EMPTY@")]
                if len(tainted_outs) != len(op.output_names()) or is_opt:
                    raise NotImplementedError(
                        f"update-section op {op.type} "
                        f"({op.output_names()}) depends on forward "
                        "activations or unstacked stage state — not "
                        "supported under PipelineExecutor (grad-transform "
                        "ops on staged params, per-param hooks)")
                plan.append(("skip_grad", op))
        # every stage-rest optimizer op must mirror its stage-0 twin
        k_of = {}
        for s, names in enumerate(self._stage_params):
            for k, n in enumerate(names):
                k_of[n] = k
        sig0 = {}
        for kind, op in plan:
            if kind == "run" and op.inputs.get("Param", [None])[0] in stage0:
                sig0[k_of[op.inputs["Param"][0]]] = (op.type,
                                                     _attr_sig(op.attrs))
        for kind, op in plan:
            if kind != "skip_stage_opt":
                continue
            k = k_of[op.inputs["Param"][0]]
            if sig0.get(k) != (op.type, _attr_sig(op.attrs)):
                raise ValueError(
                    f"optimizer op for staged param "
                    f"{op.inputs['Param'][0]} differs from stage 0's "
                    "(type/attrs) — stacked update would be wrong")
        missing = [n for n in stage0 if n not in self._group_opt_ops]
        if missing:
            raise ValueError(
                f"staged params {missing} have no optimizer op")
        self._update_plan = plan
        # accumulators of stage-0 opt ops: stacked like their params.
        # slots beyond Param/Grad/LearningRate reference accumulators
        self._stage_acc: Dict[str, List[str]] = {}
        self._acc_owner: Dict[str, str] = {}
        for pname, op0 in self._group_opt_ops.items():
            k = k_of[pname]
            accs = [n for slot, ns in op0.inputs.items()
                    if slot not in ("Param", "Grad", "LearningRate")
                    for n in ns if n in self._persistable]
            for acc in accs:
                self._acc_owner[acc] = pname
                per_stage = [acc]
                for s in range(1, len(self._stage_params)):
                    twin = next(
                        op for kind, op in self._update_plan
                        if kind == "skip_stage_opt"
                        and op.inputs["Param"][0]
                        == self._stage_params[s][k])
                    slot = next(sl for sl, ns in op0.inputs.items()
                                if acc in ns)
                    per_stage.append(twin.inputs[slot][
                        op0.inputs[slot].index(acc)])
                self._stage_acc[acc] = per_stage
        # beta-pow style shared accumulators must not be stage-stacked
        # twice; sanity: an acc name appears in exactly one group
        flat = [n for v in self._stage_acc.values() for n in v]
        if len(flat) != len(set(flat)):
            raise NotImplementedError(
                "optimizer accumulators shared across staged params are "
                "not supported")

    # ------------------------------------------------------------------
    # state placement
    # ------------------------------------------------------------------
    def _init_states(self, scope, shard_opt):
        mesh, dp = self.mesh, self.mesh.shape[self.batch_axis]
        pp_ax, dp_ax = self.stage_axis, self.batch_axis
        stage0 = self._stage_params[0]
        stacked_members = {n for sp in self._stage_params[1:] for n in sp}
        for accs in self._stage_acc.values():
            stacked_members |= set(accs[1:])

        def val(n):
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    f"state var {n!r} not produced by the startup program")
            return np.asarray(v)

        def tp_padded(p0, shape):
            """The param's tp spec (pipeline_stage dim EXCLUDED) padded
            with Nones to len(shape); only divisible dims keep the tp
            axis (GSPMD pads otherwise — correct but wasteful on the
            tiny virtual-mesh shapes)."""
            ndim = len(shape)
            spec = list(self.tp_param_specs.get(p0, ())) if self.tp_axis \
                else []
            spec += [None] * (ndim - len(spec))
            tp_n = mesh.shape[self.tp_axis] if self.tp_axis else 1
            return [None if (s == self.tp_axis and shape[i] % tp_n)
                    else s for i, s in enumerate(spec[:ndim])]

        unknown = sorted(k for k in self._param_shardings
                         if k not in self._persistable)
        if unknown:
            raise ValueError(
                f"param_shardings names {unknown} are not persistable "
                "vars of this program (typo?)")
        staged_keys = sorted(k for k in self._param_shardings
                             if k in set(stage0) or k in stacked_members)
        if staged_keys:
            raise ValueError(
                f"param_shardings entries {staged_keys} name STAGED "
                "params — staged weights are sharded by the tp_axis "
                "derivation (tp_param_specs), not per-name specs")

        states, shardings = {}, {}
        self._state_map = {}
        # stacked parameter groups + their accumulators
        for k, p0 in enumerate(stage0):
            stack = np.stack([val(sp[k]) for sp in self._stage_params])
            states[p0] = stack
            shardings[p0] = NamedSharding(
                mesh, P(pp_ax, *tp_padded(p0, stack.shape[1:])))
            for s, sp in enumerate(self._stage_params):
                self._state_map[sp[k]] = ("stacked", p0, s)
        for acc0, names in self._stage_acc.items():
            stack = np.stack([val(n) for n in names])
            states[acc0] = stack
            # accumulator shards exactly like its param (same shape),
            # plus ZeRO-1: the first still-free dim additionally shards
            # over dp when divisible
            spec = [pp_ax] + tp_padded(self._acc_owner.get(acc0, acc0),
                                       stack.shape[1:])
            if shard_opt:
                for i in range(1, stack.ndim):
                    if (spec[i] is None and stack.shape[i] % dp == 0
                            and stack.shape[i] >= dp):
                        spec[i] = dp_ax
                        break
            shardings[acc0] = NamedSharding(mesh, P(*spec))
            for s, n in enumerate(names):
                self._state_map[n] = ("stacked", acc0, s)
        # every other persistable the program touches
        for n in sorted(self._persistable):
            if n in states or n in stacked_members or n in self._state_map:
                continue
            if not scope.has_var(n) or scope.find_var(n) is None:
                continue  # produced mid-program (e.g. aux writes only)
            v = val(n)
            spec = self._param_shardings.get(n)
            if spec is None:
                # accumulator inherits its parameter's explicit spec
                # (same policy as ParallelExecutor._spec_for)
                for pname, ps in self._param_shardings.items():
                    if (n.startswith(pname + "_") and n.endswith("_acc")
                            and tuple(v.shape) and len(ps) <= v.ndim):
                        spec = ps
                        break
            if spec is None:
                spec = P()
                if (shard_opt and n.endswith("_acc") and v.ndim >= 1
                        and v.shape[0] % dp == 0 and v.shape[0] >= dp):
                    spec = P(dp_ax)
            states[n] = v
            shardings[n] = NamedSharding(mesh, spec)
            self._state_map[n] = ("direct", n, None)
        self._state_shardings = shardings
        self._states = {n: jax.device_put(v, shardings[n])
                        for n, v in states.items()}
        self._data_sharding = NamedSharding(mesh, P(self.batch_axis))

    # ------------------------------------------------------------------
    # the jitted train step
    # ------------------------------------------------------------------
    def _make_stage_fn_factory(self):
        """-> make_stage_fn(key) -> stage_fn(pvals, h, t), shared by the
        GPipe and 1F1B schedules.  The per-(stage, op) SERIAL rng-tag
        table is a closed-over constant indexed by the stage's
        axis_index: the one traced stage body runs stage 0's op descs for
        every stage, so a random op (dropout) must derive its key from
        the op identity the SERIAL executor would use for THAT stage
        (ExecContext.tag_lookup)."""
        import zlib

        from ..core import registry as op_registry
        from ..core.execution import _op_rng_tag

        mesh = self.mesh
        stage0 = list(self._stage_params[0])
        s0_ops = tuple(self._stage_ops[0])
        trunk_in, s0_out = self._trunk_in, self._stage_out[0]
        n_micro, batch_axis, stage_axis = (self.n_micro, self.batch_axis,
                                           self.stage_axis)
        sp_axis = self.sp_axis
        has_random = self._trunk_has_random
        stage_tags = np.zeros((len(self._stage_ops), len(s0_ops)),
                              np.int32)
        for s, sops in enumerate(self._stage_ops):
            for j, op in enumerate(sops):
                info = op_registry.get_op_info(op.type)
                stage_tags[s, j] = (
                    zlib.crc32(_op_rng_tag(op, info).encode())
                    & 0x7FFFFFFF)
        op_pos = {id(op): j for j, op in enumerate(s0_ops)}

        def make_stage_fn(key):
            def stage_fn(pvals, h, t):
                env = DictEnv(dict(zip(stage0, pvals)))
                env.set(trunk_in, h)
                ctx = ExecContext(
                    key if has_random else jax.random.key(0),
                    compiled=True)
                if sp_axis:
                    # the attention lowering rings K/V over this axis
                    ctx.sp_axis = sp_axis
                    ctx.sp_size = mesh.shape[sp_axis]
                if has_random:
                    tag_row = jnp.asarray(stage_tags)[
                        jax.lax.axis_index(stage_axis)]
                    ctx.tag_lookup = lambda op: (
                        tag_row[op_pos[id(op)]]
                        if id(op) in op_pos else None)
                    # global row offset of this (microbatch, dp shard):
                    # dropout keys masks by batch position, so the
                    # pipelined draw equals the serial full-batch draw
                    mb_loc = h.shape[0]
                    dp = mesh.shape[batch_axis]
                    micro = jnp.clip(
                        t - jax.lax.axis_index(stage_axis), 0,
                        n_micro - 1)
                    ctx.row_offset = (
                        micro * (mb_loc * dp)
                        + jax.lax.axis_index(batch_axis) * mb_loc)
                    if sp_axis:
                        ctx.rng_seq_block = jax.lax.axis_index(sp_axis)
                for op in s0_ops:
                    run_op(ctx, op, env)
                return env.get(s0_out)

            return stage_fn

        return make_stage_fn

    def _make_jit_step(self):
        if self.schedule == "1f1b":
            return self._make_jit_step_1f1b()
        return self._make_jit_step_gpipe()

    def _make_jit_step_gpipe(self):
        mesh = self.mesh
        stage0 = list(self._stage_params[0])
        pre_ops = tuple(self._pre_ops)
        post_ops = tuple(self._post_ops)
        s0_ops = tuple(self._stage_ops[0])
        trunk_in, trunk_out = self._trunk_in, self._trunk_out
        s0_out = self._stage_out[0]
        loss_name, fetch_names = self._loss_name, self.fetch_names
        n_micro, batch_axis, stage_axis = (self.n_micro, self.batch_axis,
                                           self.stage_axis)
        aux_writes = list(self._aux_writes)
        plan = tuple(self._update_plan)
        trainable = [n for n in self._trainable if n in self._states]
        outer_trainable = [n for n in trainable if n not in stage0]

        tp_axis, sp_axis = self.tp_axis, self.sp_axis
        has_random = self._trunk_has_random
        make_stage_fn = self._make_stage_fn_factory()

        def forward(outer_p, stack_p, rest, feeds, key):
            env = DictEnv({**rest, **outer_p, **feeds})
            ctx = ExecContext(key, compiled=True)
            for op in pre_ops:
                run_op(ctx, op, env)
            h = env.get(trunk_in)
            h = microbatch(h, n_micro)
            h = spmd_pipeline(make_stage_fn(key), tuple(stack_p), h,
                              mesh, axis=stage_axis,
                              batch_axis=batch_axis,
                              auto_axes=(tp_axis,) if tp_axis else (),
                              seq_axis=sp_axis, with_tick=True)
            env.set(trunk_out, unmicrobatch(h))
            for op in post_ops:
                run_op(ctx, op, env)
            loss = jnp.sum(env.get(loss_name))
            fetches = {n: env.get(n) for n in fetch_names}
            aux_new = {n: env.d[n] for n in aux_writes if n in env.d}
            return loss, (fetches, aux_new)

        grad_fn = jax.value_and_grad(forward, argnums=(0, 1),
                                     has_aux=True)

        def step(feeds, states, key):
            outer_p = {n: states[n] for n in outer_trainable}
            stack_p = [states[n] for n in stage0]
            rest = {n: v for n, v in states.items()
                    if n not in outer_trainable and n not in stage0}
            (loss, (fetches, aux_new)), (g_outer, g_stack) = grad_fn(
                outer_p, stack_p, rest, feeds, key)

            # --- the Program's own update ops on the computed grads ----
            env = DictEnv({**states, **aux_new})
            for n, g in g_outer.items():
                env.set(grad_var_name(n), g)
            for n, g in zip(stage0, g_stack):
                env.set(grad_var_name(n), g)
            ctx = ExecContext(jax.random.fold_in(key, 1), compiled=True)
            for kind, op in plan:
                if kind == "run":
                    run_op(ctx, op, env)
            # env.d already holds aux_new (merged at construction) and
            # every update-op write; anything untouched keeps its old value
            new_states = {n: env.d.get(n, states[n]) for n in states}
            return fetches, loss, new_states

        out_sh = {n: self._state_shardings[n] for n in self._states}
        return jax.jit(step, out_shardings=(None, None, out_sh),
                       donate_argnums=(1,))

    def _make_jit_step_1f1b(self):
        """The 1F1B schedule (parallel/pipeline.spmd_pipeline_1f1b): one
        scan interleaves forward and backward microbatches with vjp
        residuals in an O(pp) ring buffer — the long-n_micro /
        tight-HBM configuration.  The post section runs per microbatch
        as the schedule's last_fn (its params' grads accumulate inside
        the scan); pre-section grads come from the schedule's dx through
        jax.vjp of the pre ops; fetches are recomputed exactly on the
        full batch from the collected last-stage outputs (dropout's
        batch-position keying makes the recompute bit-identical to the
        per-microbatch draws)."""
        from .pipeline import spmd_pipeline_1f1b

        mesh = self.mesh
        stage0 = list(self._stage_params[0])
        pre_ops = tuple(self._pre_ops)
        post_ops = tuple(self._post_ops)
        trunk_in, trunk_out = self._trunk_in, self._trunk_out
        loss_name, fetch_names = self._loss_name, self.fetch_names
        n_micro, batch_axis, stage_axis = (self.n_micro, self.batch_axis,
                                           self.stage_axis)
        aux_writes = list(self._aux_writes)
        plan = tuple(self._update_plan)
        trainable = [n for n in self._trainable if n in self._states]
        outer_trainable = [n for n in trainable if n not in stage0]
        tp_axis, sp_axis = self.tp_axis, self.sp_axis
        make_stage_fn = self._make_stage_fn_factory()

        pre_reads = {n for op in pre_ops for n in op.input_names()}
        post_reads = {n for op in post_ops for n in op.input_names()}
        pre_params = [n for n in outer_trainable if n in pre_reads]
        post_params = [n for n in outer_trainable if n in post_reads]
        # non-trainable states the post section reads (closure, replicated)
        post_rest = [n for n in sorted(post_reads)
                     if n in self._states and n not in post_params
                     and n not in stage0]
        y_names = ([n for n in self.feed_names if n in post_reads]
                   + self._side_vars)
        dp = mesh.shape[batch_axis]
        sp = mesh.shape[sp_axis] if sp_axis else 1
        # batch-mean loss combination (see _validate_1f1b)
        scale = 1.0 / (n_micro * dp * sp)

        def make_last_fn(key, lrest):
            def last_fn(lp, h, y, m):
                env = DictEnv({**lrest, **lp, **y})
                env.set(trunk_out, h)
                ctx = ExecContext(key, compiled=True)
                mb_loc = h.shape[0]
                ctx.row_offset = (m * (mb_loc * dp)
                                  + jax.lax.axis_index(batch_axis)
                                  * mb_loc)
                if sp_axis:
                    ctx.rng_seq_block = jax.lax.axis_index(sp_axis)
                for op in post_ops:
                    run_op(ctx, op, env)
                return jnp.sum(env.get(loss_name)) * scale

            return last_fn

        def step(feeds, states, key):
            stack_p = [states[n] for n in stage0]
            rest = {n: v for n, v in states.items()
                    if n not in outer_trainable and n not in stage0}
            pre_p = {n: states[n] for n in pre_params}
            lp = {n: states[n] for n in post_params}
            lrest = {n: states[n] for n in post_rest}

            # full-batch pre pass: trunk input, side values, pre aux
            env = DictEnv({**rest,
                           **{n: states[n] for n in outer_trainable},
                           **feeds})
            ctx = ExecContext(key, compiled=True)
            for op in pre_ops:
                run_op(ctx, op, env)
            aux_new = {n: env.d[n] for n in aux_writes if n in env.d}
            x_mb = microbatch(env.get(trunk_in), n_micro)
            y_mb = {n: microbatch(env.get(n), n_micro) for n in y_names}

            loss_sum, outs, g_stack, g_last, dx = spmd_pipeline_1f1b(
                make_stage_fn(key), make_last_fn(key, lrest),
                tuple(stack_p), lp, x_mb, y_mb, mesh, axis=stage_axis,
                batch_axis=batch_axis,
                auto_axes=(tp_axis,) if tp_axis else (),
                seq_axis=sp_axis, with_tick=True)

            # pre-section grads from the schedule's input cotangents
            # (XLA CSEs this re-trace with the pre pass above: same key,
            # same ops, same operands)
            def pre_fn(pp_):
                env2 = DictEnv({**rest, **lp, **pp_, **feeds})
                ctx2 = ExecContext(key, compiled=True)
                for op in pre_ops:
                    run_op(ctx2, op, env2)
                return env2.get(trunk_in)

            _, pre_vjp = jax.vjp(pre_fn, pre_p)
            (g_pre,) = pre_vjp(unmicrobatch(dx))

            # fetches: exact full-batch post on the collected outputs
            env.set(trunk_out, unmicrobatch(outs))
            for op in post_ops:
                run_op(ctx, op, env)
            loss = jnp.sum(env.get(loss_name))
            fetches = {n: env.get(n) for n in fetch_names}

            # --- the Program's own update ops on the computed grads ----
            envU = DictEnv({**states, **aux_new})
            for n in outer_trainable:
                g = None
                if n in g_pre:
                    g = g_pre[n]
                if n in g_last:
                    g = g_last[n] if g is None else g + g_last[n]
                if g is not None:
                    envU.set(grad_var_name(n), g)
            for n, g in zip(stage0, g_stack):
                envU.set(grad_var_name(n), g)
            ctxU = ExecContext(jax.random.fold_in(key, 1), compiled=True)
            for kind, op in plan:
                if kind == "run":
                    run_op(ctxU, op, envU)
            new_states = {n: envU.d.get(n, states[n]) for n in states}
            return fetches, loss, new_states

        out_sh = {n: self._state_shardings[n] for n in self._states}
        return jax.jit(step, out_shardings=(None, None, out_sh),
                       donate_argnums=(1,))

    def _refresh_trace_flags(self):
        # see parallel/executor.py:_refresh_trace_flags — amp_bf16 and
        # flash_min_seq_k are read at trace time
        if _trace_flags() != self._trace_flags_state:
            self._jit_step = self._make_jit_step()
            self._trace_flags_state = _trace_flags()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def run(self, feed: Dict, fetch_list=None, return_numpy=True):
        import time as _time

        t0 = _time.perf_counter()
        self._refresh_trace_flags()
        fetch_names = ([v.name if isinstance(v, Variable) else str(v)
                        for v in fetch_list]
                       if fetch_list is not None else self.fetch_names)
        assert fetch_names == self.fetch_names, \
            "fetch_list must match construction-time fetch_list"
        with obs_tracing.span("executor.run", mode="pipeline"):
            dp = self.mesh.shape[self.batch_axis]
            feeds = {}
            for n, v in feed.items():
                v = np.asarray(v)
                if v.shape[0] % self.n_micro:
                    raise ValueError(
                        f"batch {v.shape[0]} not divisible by n_micro "
                        f"{self.n_micro}")
                if (v.shape[0] // self.n_micro) % dp:
                    raise ValueError(
                        f"microbatch {v.shape[0] // self.n_micro} not "
                        f"divisible by the '{self.batch_axis}' axis "
                        f"({dp})")
                feeds[n] = jax.device_put(v, self._data_sharding)
            key = jax.random.fold_in(jax.random.key(self._seed),
                                     self._step)
            self._step += 1
            fetches, _loss, self._states = self._jit_step(
                feeds, self._states, key)
            out = [fetches[n] for n in fetch_names]
            if return_numpy:
                out = [np.asarray(v) for v in out]
        if obs_metrics.enabled():
            if not hasattr(self, "_m_run"):
                from .executor import _M_RUN_SECONDS, _PE_IDS
                self._m_run_id = f"pipe{next(_PE_IDS)}"
                self._m_run = _M_RUN_SECONDS.labels(
                    exe=self._m_run_id, mode="pipeline")
            self._m_run.observe(_time.perf_counter() - t0)
        return out

    def close(self):
        """Reclaim this instance's registry series (per-instance
        telemetry contract, same as ParallelExecutor.close)."""
        if hasattr(self, "_m_run"):
            from .executor import _M_RUN_SECONDS
            _M_RUN_SECONDS.remove(exe=self._m_run_id, mode="pipeline")

    def state(self, name, return_numpy=True):
        kind, store, idx = self._state_map[name]
        v = self._states[store]
        if kind == "stacked":
            v = v[idx]
        return np.asarray(v) if return_numpy else v

    def compiled_collectives(self, feed: Dict) -> Dict[str, int]:
        """Collective-op counts in the optimized HLO of the train step for
        `feed`'s shapes (collective-permute = pipeline hops; all-reduce =
        dp grad sums) — the communication-structure pin used by tests and
        run_scaling --virtual."""
        feeds = {
            n: jax.ShapeDtypeStruct(np.asarray(v).shape,
                                    np.asarray(v).dtype,
                                    sharding=self._data_sharding)
            for n, v in feed.items()
        }
        key = jax.random.key(self._seed)
        txt = self._jit_step.lower(feeds, self._states, key) \
            .compile().as_text()
        return count_collectives(txt)
