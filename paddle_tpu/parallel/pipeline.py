"""SPMD pipeline parallelism (GPipe schedule) over a 'pp' mesh axis.

The reference's only pipeline-ish facility is per-layer device placement in
the legacy engine (ParallelNeuralNetwork,
/root/reference/paddle/gserver/gradientmachines/ParallelNeuralNetwork.h —
layers annotated with deviceId run on different GPUs).  The TPU rebuild
expresses the same capability the XLA way: every pipeline stage runs the
SAME traced computation under `shard_map`, each device holds only its
stage's parameters (a stacked pytree sharded on the leading axis), and
activations hop stage->stage with one `lax.ppermute` (one ICI hop) per
schedule tick.  The whole schedule is written with `lax.scan`, so JAX's
autodiff derives the reverse (backward) pipeline automatically — no
hand-written 1F1B bookkeeping.

Constraints (documented, checked): every stage maps activations of one
fixed shape to the same shape — put embedding/classifier layers outside
the pipelined trunk (the usual GPipe decomposition).  Bubble fraction is
(pp-1)/(n_micro+pp-1), so use n_micro >= ~4*pp for real runs.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["spmd_pipeline", "stack_stage_params", "microbatch",
           "unmicrobatch"]


def stack_stage_params(per_stage: Sequence[Any]):
    """Stack a list of per-stage parameter pytrees along a new leading
    axis (to be sharded over the pp mesh axis)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage)


def microbatch(x, n_micro: int):
    """[batch, ...] -> [n_micro, batch/n_micro, ...]"""
    if x.shape[0] % n_micro:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by n_micro {n_micro}")
    return x.reshape(n_micro, x.shape[0] // n_micro, *x.shape[1:])


def unmicrobatch(y):
    """[n_micro, mb, ...] -> [n_micro*mb, ...]"""
    return y.reshape(y.shape[0] * y.shape[1], *y.shape[2:])


def spmd_pipeline(stage_fn: Callable, stage_params, x, mesh: Mesh,
                  axis: str = "pp", batch_axis: str | None = None,
                  param_specs=None, auto_axes: Sequence[str] = (),
                  seq_axis: str | None = None, with_tick: bool = False):
    """Run `stage_fn` as a `pp`-stage GPipe pipeline.

    stage_fn:     (params, activation[mb, ...]) -> activation[mb, ...]
                  (same callable for every stage; per-stage behavior comes
                  from the per-stage params)
    stage_params: pytree whose leaves are stacked [pp, ...] per-stage
                  parameters (see stack_stage_params)
    x:            [n_micro, mb, ...] microbatched input (see microbatch)
    batch_axis:   optional mesh axis to shard the microbatch dim over
                  (dp x pp composition: each dp replica pipelines its own
                  batch shard; param grads psum over dp automatically in
                  shard_map's backward)
    param_specs:  optional pytree of PartitionSpecs (matching
                  stage_params' structure, or a single spec) whose FIRST
                  entry must be `axis` — lets stage weights also shard
                  over a tensor-parallel mesh axis (dp x pp x tp
                  composition); the stage_fn is then responsible for the
                  tp collectives (e.g. psum over 'tp' after a
                  row-parallel matmul).  Default: P(axis) on every leaf.
    auto_axes:    mesh axes left OUT of shard_map's manual set: arrays
                  keep (and propagate) GSPMD shardings over them inside
                  the stage body, so a tensor-parallel axis needs no
                  hand-written collectives at all — annotate the stacked
                  params' non-leading dims with the axis (NamedSharding
                  at device_put) and XLA inserts the Megatron psum where
                  sharding propagation demands it.  This is how
                  PipelineExecutor composes tp with a generic op-lowering
                  stage body (manual specs can't: op lowerings see global
                  shapes).  param_specs then must reference only manual
                  axes (pass the default P(axis)).
    seq_axis:     optional manual mesh axis to shard the activations'
                  dim 2 (the sequence dim of a [n_micro, mb, S, ...]
                  stream) — sequence parallelism; the stage body then
                  runs on local sequence blocks and its attention op must
                  use ring collectives over this axis (the
                  flash_attention lowering does when the ExecContext
                  carries sp_axis).
    returns:      [n_micro, mb, ...] last-stage outputs (sharded over
                  `batch_axis`/`seq_axis` if given, otherwise replicated).

    Differentiable end-to-end: grad through this function yields the
    reverse pipeline schedule, with per-stage param grads sharded exactly
    like the params.  During the pp-1 fill/drain bubble ticks stages run
    on recirculated real microbatch data (never synthetic zeros), so a
    stage_fn that divides by activation statistics stays NaN-free.
    """
    pp = mesh.shape[axis]
    n_micro = x.shape[0]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != pp:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != pipeline "
                f"axis size {pp}: one stacked stage per '{axis}' device "
                "(a mismatch would silently drop stages)")
    if seq_axis:
        x_spec = P(None, batch_axis, seq_axis)
    else:
        x_spec = P(None, batch_axis) if batch_axis else P()
    if param_specs is None:
        param_specs = P(axis)
    else:
        for spec in jax.tree_util.tree_leaves(
                param_specs, is_leaf=lambda s: isinstance(s, P)):
            if not len(spec) or spec[0] != axis:
                raise ValueError(
                    f"param_specs leaf {spec} must lead with the pipeline "
                    f"axis {axis!r} (stacked stage dim)")
    sm_kwargs = {}
    if auto_axes:
        manual = set(mesh.axis_names) - set(auto_axes)
        missing = set(auto_axes) - set(mesh.axis_names)
        if missing:
            raise ValueError(f"auto_axes {missing} not in mesh axes "
                             f"{mesh.axis_names}")
        for spec in jax.tree_util.tree_leaves(
                (param_specs, x_spec),
                is_leaf=lambda s: isinstance(s, P)):
            bad = set(spec) & set(auto_axes)
            if bad:
                raise ValueError(
                    f"spec {spec} references auto axis {bad}: auto-axis "
                    "sharding comes from the arrays' NamedShardings, not "
                    "from shard_map specs")
        sm_kwargs["axis_names"] = manual

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec, **sm_kwargs)
    def _run(params_blk, xs):
        stage = jax.lax.axis_index(axis)
        params_local = jax.tree_util.tree_map(lambda p: p[0], params_blk)
        # drain ticks recirculate real data (see docstring); their outputs
        # are sliced away below
        pad = jnp.broadcast_to(xs[:1], (pp - 1,) + xs.shape[1:])
        stream = jnp.concatenate([xs, pad], axis=0)
        state0 = jax.lax.stop_gradient(xs[0])
        state0 = jax.lax.pcast(state0, (axis,), to="varying")

        def tick(state, xt_t):
            xt, t = xt_t
            # stage 0 ingests from the stream; others from the neighbor
            inp = jnp.where(stage == 0, xt, state)
            # with_tick: stage_fn(params, x, tick_index) — the schedule
            # position, from which a stage derives its microbatch index
            # (t - stage) for e.g. per-microbatch PRNG offsets
            out = (stage_fn(params_local, inp, t) if with_tick
                   else stage_fn(params_local, inp))
            nxt = jax.lax.ppermute(
                out, axis, [(i, (i + 1) % pp) for i in range(pp)])
            return nxt, out

        _, ys = jax.lax.scan(
            tick, state0,
            (stream, jnp.arange(stream.shape[0], dtype=jnp.int32)))
        # keep only the last stage's real emissions (drop the pp-1 warm-up
        # ticks BEFORE the psum so bubble outputs never cross the ICI),
        # then psum over the (otherwise-zero) mask to replicate them
        ys = jax.lax.slice_in_dim(ys, pp - 1, pp - 1 + n_micro, axis=0)
        mask = (stage == pp - 1).astype(ys.dtype)
        return jax.lax.psum(ys * mask, axis)

    return _run(stage_params, x)
