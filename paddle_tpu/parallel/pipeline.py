"""SPMD pipeline parallelism (GPipe schedule) over a 'pp' mesh axis.

The reference's only pipeline-ish facility is per-layer device placement in
the legacy engine (ParallelNeuralNetwork,
/root/reference/paddle/gserver/gradientmachines/ParallelNeuralNetwork.h —
layers annotated with deviceId run on different GPUs).  The TPU rebuild
expresses the same capability the XLA way: every pipeline stage runs the
SAME traced computation under `shard_map`, each device holds only its
stage's parameters (a stacked pytree sharded on the leading axis), and
activations hop stage->stage with one `lax.ppermute` (one ICI hop) per
schedule tick.  Two schedules:

  * `spmd_pipeline` (GPipe): forward scan; JAX's autodiff derives the
    reverse pipeline automatically.  Fewest steps, but the scan buffers
    residuals for every tick — activation memory grows with n_micro.
  * `spmd_pipeline_1f1b`: forward and backward microbatches interleave
    in ONE scan with vjp residuals in an O(pp) ring buffer — flat
    activation memory for long n_micro (docs/design/parallelism.md has
    the measured table and the schedule math).

Constraints (documented, checked): every stage maps activations of one
fixed shape to the same shape — put embedding/classifier layers outside
the pipelined trunk (the usual GPipe decomposition).  GPipe bubble
fraction is (pp-1)/(n_micro+pp-1), so use n_micro >= ~4*pp for real
runs; `bubble_fraction` covers both schedules.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import pvary, shard_map

__all__ = ["spmd_pipeline", "spmd_pipeline_1f1b", "stack_stage_params",
           "microbatch", "unmicrobatch", "schedule_steps",
           "bubble_fraction"]


def stack_stage_params(per_stage: Sequence[Any]):
    """Stack a list of per-stage parameter pytrees along a new leading
    axis (to be sharded over the pp mesh axis)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage)


def microbatch(x, n_micro: int):
    """[batch, ...] -> [n_micro, batch/n_micro, ...]"""
    if x.shape[0] % n_micro:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by n_micro {n_micro}")
    return x.reshape(n_micro, x.shape[0] // n_micro, *x.shape[1:])


def unmicrobatch(y):
    """[n_micro, mb, ...] -> [n_micro*mb, ...]"""
    return y.reshape(y.shape[0] * y.shape[1], *y.shape[2:])


def spmd_pipeline(stage_fn: Callable, stage_params, x, mesh: Mesh,
                  axis: str = "pp", batch_axis: str | None = None,
                  param_specs=None, auto_axes: Sequence[str] = (),
                  seq_axis: str | None = None, with_tick: bool = False):
    """Run `stage_fn` as a `pp`-stage GPipe pipeline.

    stage_fn:     (params, activation[mb, ...]) -> activation[mb, ...]
                  (same callable for every stage; per-stage behavior comes
                  from the per-stage params)
    stage_params: pytree whose leaves are stacked [pp, ...] per-stage
                  parameters (see stack_stage_params)
    x:            [n_micro, mb, ...] microbatched input (see microbatch)
    batch_axis:   optional mesh axis to shard the microbatch dim over
                  (dp x pp composition: each dp replica pipelines its own
                  batch shard; param grads psum over dp automatically in
                  shard_map's backward)
    param_specs:  optional pytree of PartitionSpecs (matching
                  stage_params' structure, or a single spec) whose FIRST
                  entry must be `axis` — lets stage weights also shard
                  over a tensor-parallel mesh axis (dp x pp x tp
                  composition); the stage_fn is then responsible for the
                  tp collectives (e.g. psum over 'tp' after a
                  row-parallel matmul).  Default: P(axis) on every leaf.
    auto_axes:    mesh axes left OUT of shard_map's manual set: arrays
                  keep (and propagate) GSPMD shardings over them inside
                  the stage body, so a tensor-parallel axis needs no
                  hand-written collectives at all — annotate the stacked
                  params' non-leading dims with the axis (NamedSharding
                  at device_put) and XLA inserts the Megatron psum where
                  sharding propagation demands it.  This is how
                  PipelineExecutor composes tp with a generic op-lowering
                  stage body (manual specs can't: op lowerings see global
                  shapes).  param_specs then must reference only manual
                  axes (pass the default P(axis)).
    seq_axis:     optional manual mesh axis to shard the activations'
                  dim 2 (the sequence dim of a [n_micro, mb, S, ...]
                  stream) — sequence parallelism; the stage body then
                  runs on local sequence blocks and its attention op must
                  use ring collectives over this axis (the
                  flash_attention lowering does when the ExecContext
                  carries sp_axis).
    returns:      [n_micro, mb, ...] last-stage outputs (sharded over
                  `batch_axis`/`seq_axis` if given, otherwise replicated).

    Differentiable end-to-end: grad through this function yields the
    reverse pipeline schedule, with per-stage param grads sharded exactly
    like the params.  During the pp-1 fill/drain bubble ticks stages run
    on recirculated real microbatch data (never synthetic zeros), so a
    stage_fn that divides by activation statistics stays NaN-free.
    """
    pp = mesh.shape[axis]
    n_micro = x.shape[0]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != pp:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != pipeline "
                f"axis size {pp}: one stacked stage per '{axis}' device "
                "(a mismatch would silently drop stages)")
    if seq_axis:
        x_spec = P(None, batch_axis, seq_axis)
    else:
        x_spec = P(None, batch_axis) if batch_axis else P()
    if param_specs is None:
        param_specs = P(axis)
    else:
        for spec in jax.tree_util.tree_leaves(
                param_specs, is_leaf=lambda s: isinstance(s, P)):
            if not len(spec) or spec[0] != axis:
                raise ValueError(
                    f"param_specs leaf {spec} must lead with the pipeline "
                    f"axis {axis!r} (stacked stage dim)")
    sm_kwargs = {}
    if auto_axes:
        manual = set(mesh.axis_names) - set(auto_axes)
        missing = set(auto_axes) - set(mesh.axis_names)
        if missing:
            raise ValueError(f"auto_axes {missing} not in mesh axes "
                             f"{mesh.axis_names}")
        for spec in jax.tree_util.tree_leaves(
                (param_specs, x_spec),
                is_leaf=lambda s: isinstance(s, P)):
            bad = set(spec) & set(auto_axes)
            if bad:
                raise ValueError(
                    f"spec {spec} references auto axis {bad}: auto-axis "
                    "sharding comes from the arrays' NamedShardings, not "
                    "from shard_map specs")
        sm_kwargs["axis_names"] = manual

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec, **sm_kwargs)
    def _run(params_blk, xs):
        stage = jax.lax.axis_index(axis)
        params_local = jax.tree_util.tree_map(lambda p: p[0], params_blk)
        # drain ticks recirculate real data (see docstring); their outputs
        # are sliced away below
        pad = jnp.broadcast_to(xs[:1], (pp - 1,) + xs.shape[1:])
        stream = jnp.concatenate([xs, pad], axis=0)
        state0 = jax.lax.stop_gradient(xs[0])
        state0 = pvary(state0, (axis,))

        def tick(state, xt_t):
            xt, t = xt_t
            # stage 0 ingests from the stream; others from the neighbor
            inp = jnp.where(stage == 0, xt, state)
            # with_tick: stage_fn(params, x, tick_index) — the schedule
            # position, from which a stage derives its microbatch index
            # (t - stage) for e.g. per-microbatch PRNG offsets
            out = (stage_fn(params_local, inp, t) if with_tick
                   else stage_fn(params_local, inp))
            nxt = jax.lax.ppermute(
                out, axis, [(i, (i + 1) % pp) for i in range(pp)])
            return nxt, out

        _, ys = jax.lax.scan(
            tick, state0,
            (stream, jnp.arange(stream.shape[0], dtype=jnp.int32)))
        # keep only the last stage's real emissions (drop the pp-1 warm-up
        # ticks BEFORE the psum so bubble outputs never cross the ICI),
        # then psum over the (otherwise-zero) mask to replicate them
        ys = jax.lax.slice_in_dim(ys, pp - 1, pp - 1 + n_micro, axis=0)
        mask = (stage == pp - 1).astype(ys.dtype)
        return jax.lax.psum(ys * mask, axis)

    return _run(stage_params, x)


# ---------------------------------------------------------------------------
# 1F1B schedule
# ---------------------------------------------------------------------------

def schedule_steps(n_micro: int, pp: int, schedule: str = "gpipe") -> int:
    """Schedule ticks holding one stage-computation each.  GPipe runs
    n_micro+pp-1 forward ticks (autodiff mirrors them backward); the
    lockstep 1F1B below runs n_micro+2pp-1 combined fwd+bwd steps."""
    if schedule == "gpipe":
        return n_micro + pp - 1
    if schedule == "1f1b":
        return n_micro + 2 * pp - 1
    raise ValueError(f"unknown schedule {schedule!r}")


def bubble_fraction(n_micro: int, pp: int, schedule: str = "gpipe") -> float:
    """Fraction of schedule steps a stage spends idle.  gpipe:
    (pp-1)/(n_micro+pp-1); 1f1b: (2pp-1)/(n_micro+2pp-1) — the lockstep
    SPMD 1F1B pays pp extra steps for its O(pp) activation memory (GPipe
    autodiff buffers residuals for all n_micro+pp-1 ticks)."""
    total = schedule_steps(n_micro, pp, schedule)
    return (total - n_micro) / total


def spmd_pipeline_1f1b(stage_fn: Callable, last_fn: Callable,
                       stage_params, last_params, x, y, mesh: Mesh,
                       axis: str = "pp", batch_axis: str | None = None,
                       auto_axes: Sequence[str] = (),
                       seq_axis: str | None = None,
                       with_tick: bool = False):
    """One-scan 1F1B training schedule: every scan step runs one forward
    sub-tick AND one backward sub-tick, with per-microbatch vjp residuals
    held in a ring buffer of depth 2*pp — activation memory is O(pp)
    in-flight microbatches instead of GPipe-autodiff's O(n_micro+pp)
    buffered ticks.  The price on a lockstep SPMD backend is pp extra
    schedule steps (see bubble_fraction); 1F1B here is the long-n_micro /
    tight-HBM configuration, GPipe the low-latency one.

    stage_fn:    (params, h[, tick]) -> h  (spmd_pipeline contract; tick
                 is the global fwd sub-tick index when with_tick)
    last_fn:     (last_params, h_mb, y_mb, m) -> scalar loss CONTRIBUTION
                 of microbatch m (callers targeting a batch-mean loss
                 scale by 1/n_micro inside); runs on the LAST stage right
                 after its forward — its vjp seeds the backward wave.
    stage_params: stacked [pp, ...] pytree (stack_stage_params)
    last_params:  pytree, replicated
    x:           [n_micro, mb, ...] trunk inputs
    y:           pytree with leading [n_micro, ...] (labels etc.)
    returns (loss_sum, outs, stage_grads, last_grads, dx):
      loss_sum    sum of last_fn over microbatches (replicated)
      outs        [n_micro, mb, ...] last-stage forward outputs
      stage_grads stacked like stage_params
      last_grads  like last_params (replicated)
      dx          [n_micro, mb, ...] cotangents w.r.t. x

    Schedule (stage s, microbatch m, step t): forward at t = s + m (as
    GPipe); backward at t = m + 2pp - 1 - s; the last stage's loss vjp
    seed is produced one step before its backward consumes it.
    Activations hop forward and cotangents hop backward with one
    ppermute each per step.
    """
    pp = mesh.shape[axis]
    n_micro = x.shape[0]
    T = schedule_steps(n_micro, pp, "1f1b")
    BUF = 2 * pp
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != pp:
            raise ValueError(
                f"stage_params leading dim {leaf.shape[0]} != pipeline "
                f"axis size {pp}")
    if seq_axis:
        x_spec = P(None, batch_axis, seq_axis)
    else:
        x_spec = P(None, batch_axis) if batch_axis else P()
    # y streams ride exactly with the trunk activations' sharding: under
    # sp every leaf must be [n_micro, mb, S, ...] with S the trunk's
    # seq dim (the executor validates this before choosing sp + 1f1b)
    y_spec = x_spec
    sm_kwargs = {}
    if auto_axes:
        sm_kwargs["axis_names"] = set(mesh.axis_names) - set(auto_axes)
    other_axes = tuple(a for a in (batch_axis, seq_axis) if a)

    # pad streams to T steps: x consumed by stage 0 at t = m; y consumed
    # by the last stage at t = pp - 1 + m (real data recirculates into
    # the masked ticks, keeping every traced computation finite)
    def pad_to(stream, lead):
        def pad_leaf(l):
            reps = [l[:1]] * lead + [l] + [l[:1]] * (T - lead - n_micro)
            return jnp.concatenate(reps, axis=0)
        return jax.tree_util.tree_map(pad_leaf, stream)

    x_stream = pad_to(x, 0)
    y_stream = pad_to(y, pp - 1)

    def c_psum(tree, axes):
        if not axes:
            return tree
        return jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, axes), tree)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P(), x_spec, y_spec),
        out_specs=(P(), x_spec, P(axis), P(), x_spec), **sm_kwargs)
    def _run(params_blk, last_p, xs, ys_lab):
        stage = jax.lax.axis_index(axis)
        is_last = stage == pp - 1
        is_first = stage == 0
        params_local = jax.tree_util.tree_map(lambda p: p[0], params_blk)
        if other_axes:
            # same invariant-diff hazard as last_p below: stage params
            # are replicated over dp/sp, so keep their grads per-device
            # local and do the one explicit psum at the end
            params_local = jax.tree_util.tree_map(
                lambda p: pvary(p, other_axes),
                params_local)
        # last_p arrives INVARIANT over the manual axes; differentiating
        # w.r.t. an invariant value makes the vjp transpose insert an
        # implicit psum (the transpose of the invariant->varying
        # broadcast), which would sum every device's masked-out garbage
        # gradient into each step.  pvary (cast to varying) first: grads stay
        # per-device local and the single masked psum at the end is the
        # only cross-device reduction.
        last_p_v = jax.tree_util.tree_map(
            lambda l: pvary(l, (axis,) + other_axes), last_p)

        def fwd_vjp(h, t):
            if with_tick:
                out, vjp_fn = jax.vjp(
                    lambda p, hh: stage_fn(p, hh, t), params_local, h)
            else:
                out, vjp_fn = jax.vjp(stage_fn, params_local, h)
            leaves, treedef = jax.tree_util.tree_flatten(vjp_fn)
            return out, leaves, treedef

        def last_vjp(h, yb, m):
            loss, vjp_fn = jax.vjp(
                lambda lp, hh: last_fn(lp, hh, yb, m), last_p_v, h)
            g_last, d_h = vjp_fn(jnp.ones_like(loss))
            return loss, g_last, d_h

        # prime the residual buffer with ONE real vjp (structure + finite
        # values for the masked early backward ticks)
        h0 = jax.lax.stop_gradient(xs[0])
        h0 = pvary(h0, (axis,))
        out0, leaves0, treedef = fwd_vjp(h0, 0)
        res_buf0 = [jnp.broadcast_to(l, (BUF,) + l.shape) for l in leaves0]
        zeros_g = jax.tree_util.tree_map(jnp.zeros_like, params_local)
        zeros_gl = jax.tree_util.tree_map(jnp.zeros_like, last_p_v)

        carry0 = dict(
            fwd_state=out0 * 0.0,
            bwd_state=out0 * 0.0,
            seed=out0 * 0.0,
            res_buf=res_buf0,
            g_stage=zeros_g,
            g_last=zeros_gl,
            loss=pvary(jnp.zeros((), jnp.float32),
                       (axis,) + other_axes),
        )

        fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
        bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]

        def step(c, xt):
            xb, yb, t = xt
            # ---- forward sub-tick: m_f = t - stage -------------------
            m_f = t - stage
            f_valid = (m_f >= 0) & (m_f < n_micro)
            inp = jnp.where(is_first, xb, c["fwd_state"])
            out, leaves, _ = fwd_vjp(inp, t)
            slot_f = jnp.clip(m_f, 0, n_micro - 1) % BUF
            res_buf = [
                jnp.where(
                    f_valid,
                    jax.lax.dynamic_update_index_in_dim(
                        buf, l, slot_f, 0),
                    buf)
                for buf, l in zip(c["res_buf"], leaves)]
            # last stage: loss + seed for its own backward next step
            loss_m, g_last_m, d_seed = last_vjp(out, yb, jnp.clip(
                m_f, 0, n_micro - 1))
            take = f_valid & is_last
            loss = c["loss"] + jnp.where(take, loss_m, 0.0)
            g_last = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(take, g, 0.0),
                c["g_last"], g_last_m)
            seed = jnp.where(take, d_seed, c["seed"] * 0.0)

            # ---- backward sub-tick: m_b = t + stage - (2pp - 1) ------
            m_b = t + stage - (2 * pp - 1)
            b_valid = (m_b >= 0) & (m_b < n_micro)
            slot_b = jnp.clip(m_b, 0, n_micro - 1) % BUF
            leaves_b = [
                jax.lax.dynamic_index_in_dim(buf, slot_b, 0,
                                             keepdims=False)
                for buf in res_buf]
            vjp_fn = jax.tree_util.tree_unflatten(treedef, leaves_b)
            ct = jnp.where(is_last, c["seed"], c["bwd_state"])
            g_p, d_h = vjp_fn(ct)
            g_stage = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(b_valid, g, 0.0),
                c["g_stage"], g_p)
            d_h = jnp.where(b_valid, d_h, 0.0)

            # ---- hops -----------------------------------------------
            nxt_fwd = jax.lax.ppermute(out, axis, fwd_perm)
            nxt_bwd = jax.lax.ppermute(d_h, axis, bwd_perm)
            c2 = dict(fwd_state=nxt_fwd, bwd_state=nxt_bwd, seed=seed,
                      res_buf=res_buf, g_stage=g_stage, g_last=g_last,
                      loss=loss)
            # emit: last-stage fwd outputs and first-stage dx
            return c2, (jnp.where(is_last & f_valid, out, 0.0),
                        jnp.where(is_first & b_valid, d_h, 0.0))

        ticks = jnp.arange(T, dtype=jnp.int32)
        cN, (ys_out, ys_dx) = jax.lax.scan(
            step, carry0, (xs, ys_lab, ticks))

        outs = jax.lax.psum(
            jax.lax.slice_in_dim(ys_out, pp - 1, pp - 1 + n_micro, axis=0),
            axis)
        dx = jax.lax.psum(
            jax.lax.slice_in_dim(ys_dx, 2 * pp - 1,
                                 2 * pp - 1 + n_micro, axis=0),
            axis)
        # stage grads: sum over replicas (params replicated over dp/sp),
        # re-stack over the pipeline axis via out_specs
        g_stage = c_psum(cN["g_stage"], other_axes)
        g_stage = jax.tree_util.tree_map(lambda g: g[None], g_stage)
        # last_fn grads + loss live on the last stage only
        mask = (stage == pp - 1).astype(jnp.float32)
        g_last = c_psum(
            jax.tree_util.tree_map(lambda g: g * mask, cN["g_last"]),
            (axis,) + other_axes)
        # NOTE on dp/sp: each replica accumulated loss / last-grads on its
        # OWN batch (or sequence) shard, so the psum over other_axes above
        # and here SUMS the shard contributions — last_fn must therefore
        # return a contribution normalized over the GLOBAL batch (e.g.
        # sum over its local rows / total_batch for a batch-mean loss)
        loss = jax.lax.psum(cN["loss"] * mask, (axis,) + other_axes)
        return loss, outs, g_stage, g_last, dx

    return _run(stage_params, last_params, x_stream, y_stream)
