"""Fused, pipelined parameter-server communication (client side).

The naive wire path pushed one variable per frame, one endpoint at a
time, on a single thread — a sync round over many small grads was
dominated by per-frame overhead and serialized RTTs.  This module adds
the canonical fixes (PyTorch DDP's gradient buckets, Horovod's tensor
fusion) on top of the batch verbs in parallel/pserver.py:

* **arrival-order gradient buckets** — `VariableClient.send_vars`
  packs grads in the order they arrive into buckets capped by the
  ``comm_bucket_bytes`` flag (``PADDLE_TPU_COMM_BUCKET_BYTES``) and
  ships each bucket as one ``SEND_BATCH`` frame;
* **a per-endpoint connection/worker pool** (`CommPool`) — each
  pserver gets its own client + single-thread worker, so a round's
  per-endpoint chain (bucketed sends → barrier → one batched GET) runs
  concurrently across pservers while staying ordered within each;
* **round telemetry** on the observability registry — end-to-end round
  latency, bytes moved per round by direction, and (in pserver.py)
  bucket fill/size histograms — so a Prometheus dump shows whether
  buckets actually fill and rounds actually overlap.

Wire compatibility is the client's job: a `VariableClient` whose server
answers ERR to a batch verb falls back to per-var frames permanently
for that endpoint (see pserver.py), so one `CommPool` can serve mixed
old/new pserver fleets.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing
from .pserver import VariableClient

__all__ = ["CommPool", "comm_pool", "reset_comm_pool"]

# 64 B .. 1 GiB, x4 steps — grad rounds span tiny RNN cells to
# full embedding tables
_BYTE_BUCKETS = tuple(float(1 << i) for i in range(6, 31, 2))

_M_ROUND_SECONDS = obs_metrics.histogram(
    "paddle_tpu_comm_round_seconds",
    "end-to-end pserver round latency: bucketed sends + barrier + "
    "param pull across all endpoints (send/recv op)")
_M_ROUND_BYTES = obs_metrics.histogram(
    "paddle_tpu_comm_round_bytes",
    "serialized payload bytes moved per round, by direction (frame "
    "heads excluded so the directions are comparable)",
    ("direction",), buckets=_BYTE_BUCKETS)


class CommPool:
    """Per-endpoint connection + worker pool.

    One `VariableClient` and one single-thread executor per endpoint:
    within an endpoint requests stay ordered (sends must precede the
    barrier, the barrier must precede the pull), across endpoints they
    overlap — the serial `for ep in endpoints` loop the send op used to
    run paid one full round trip chain per pserver."""

    def __init__(self, client_factory=None):
        self._factory = client_factory or VariableClient
        self._clients: Dict[str, VariableClient] = {}
        self._workers: Dict[str, ThreadPoolExecutor] = {}
        self._lock = threading.Lock()
        self._closed = False

    def client(self, endpoint: str) -> VariableClient:
        with self._lock:
            c = self._clients.get(endpoint)
            closed = self._closed
        if c is not None:
            # existing clients keep serving while close() drains the
            # workers — only NEW connections are refused, so an
            # in-flight round finishes instead of failing mid-round
            return c
        if closed:
            raise RuntimeError("CommPool is closed")
        # connect OUTSIDE the lock: a booting pserver can take
        # seconds, and other endpoints' clients must not wait on it
        c = self._factory(endpoint)
        with self._lock:
            if self._closed:
                extant = None
            else:
                extant = self._clients.setdefault(endpoint, c)
        if extant is None:
            c.close()
            raise RuntimeError("CommPool is closed")
        if extant is not c:
            c.close()
            c = extant
        return c

    def _worker(self, endpoint: str) -> ThreadPoolExecutor:
        with self._lock:
            if self._closed:
                raise RuntimeError("CommPool is closed")
            w = self._workers.get(endpoint)
            if w is None:
                w = ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix=f"comm-{endpoint}")
                self._workers[endpoint] = w
            return w

    def send_round(self, send_items: Sequence[Tuple[str, str, object]],
                   get_items: Sequence[Tuple[str, str]],
                   bucket_bytes: Optional[int] = None) -> List[object]:
        """One fused synchronous round.

        ``send_items``: [(endpoint, name, value)] grads in arrival
        order; ``get_items``: [(endpoint, name)] params to pull.  Per
        endpoint that received grads: bucketed sends, then the batch
        barrier, then one batched GET — chained on that endpoint's
        worker so endpoints overlap.  Endpoints appearing only in
        ``get_items`` are read without a barrier (recv-op semantics).
        Returns pulled values aligned with ``get_items``."""
        t0 = time.perf_counter()
        sends: Dict[str, list] = {}
        for ep, name, value in send_items:
            sends.setdefault(ep, []).append((name, value))
        gets: Dict[str, list] = {}
        for ep, name in get_items:
            gets.setdefault(ep, []).append(name)
        ctx = obs_tracing.current_context()

        def run_ep(ep):
            c = self.client(ep)
            s0, r0 = c.bytes_sent, c.bytes_recv
            with obs_tracing.activate(ctx), \
                    obs_tracing.span("comm.endpoint_round", endpoint=ep):
                if ep in sends:
                    c.send_vars(sends[ep], bucket_bytes)
                    c.send_batch_barrier()
                vals = (c.get_vars(gets[ep], bucket_bytes)
                        if ep in gets else [])
            return vals, c.bytes_sent - s0, c.bytes_recv - r0

        eps = sorted(set(sends) | set(gets))
        results: Dict[str, tuple] = {}
        if eps:
            # ALWAYS go through the per-endpoint worker — even for one
            # endpoint: two caller threads sharing the pool would
            # otherwise interleave frames on the same non-thread-safe
            # client socket; the worker is what serializes them
            futs = {}
            submit_exc = None
            for ep in eps:
                try:
                    futs[ep] = self._worker(ep).submit(run_ep, ep)
                except BaseException as e:
                    # pool closed mid-loop: stop submitting, but still
                    # drain what IS in flight below
                    submit_exc = e
                    break
            first_exc = None
            for ep, f in futs.items():
                # drain EVERY submitted future before raising: an
                # abandoned in-flight worker would race the caller's
                # error handling on the shared clients
                try:
                    results[ep] = f.result()
                except BaseException as e:
                    if first_exc is None:
                        first_exc = e
            if first_exc is None:
                first_exc = submit_exc
            if first_exc is not None:
                raise first_exc
        out, idx = [], {ep: 0 for ep in gets}
        for ep, name in get_items:
            out.append(results[ep][0][idx[ep]])
            idx[ep] += 1
        _M_ROUND_SECONDS.observe(time.perf_counter() - t0)
        _M_ROUND_BYTES.labels(direction="sent").observe(
            sum(r[1] for r in results.values()))
        _M_ROUND_BYTES.labels(direction="recv").observe(
            sum(r[2] for r in results.values()))
        return out

    def close(self):
        # order matters: mark closed (new rounds and NEW connections
        # fail fast; existing clients keep serving), drain the workers
        # so in-flight rounds finish against those live clients, and
        # only then close the sockets — closing first would let a
        # draining round register a fresh connection into an
        # already-swept pool and leak it
        with self._lock:
            self._closed = True
            workers = list(self._workers.values())
            self._workers.clear()
        for w in workers:
            w.shutdown(wait=True)
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()


_POOL: Optional[CommPool] = None
_POOL_LOCK = threading.Lock()


def comm_pool() -> CommPool:
    """The process-wide pool the send/recv ops route through."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = CommPool()
        return _POOL


def reset_comm_pool():
    """Close every pooled connection/worker (tests, cluster teardown)."""
    global _POOL
    with _POOL_LOCK:
        pool, _POOL = _POOL, None
    if pool is not None:
        pool.close()
