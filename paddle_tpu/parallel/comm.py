"""Fused, pipelined parameter-server communication (client side).

The naive wire path pushed one variable per frame, one endpoint at a
time, on a single thread — a sync round over many small grads was
dominated by per-frame overhead and serialized RTTs.  This module adds
the canonical fixes (PyTorch DDP's gradient buckets, Horovod's tensor
fusion) on top of the batch verbs in parallel/pserver.py:

* **arrival-order gradient buckets** — `VariableClient.send_vars`
  packs grads in the order they arrive into buckets capped by the
  ``comm_bucket_bytes`` flag (``PADDLE_TPU_COMM_BUCKET_BYTES``) and
  ships each bucket as one ``SEND_BATCH`` frame;
* **a per-endpoint connection/worker pool** (`CommPool`) — each
  pserver gets its own client + single-thread worker, so a round's
  per-endpoint chain (bucketed sends → barrier → one batched GET) runs
  concurrently across pservers while staying ordered within each;
* **round telemetry** on the observability registry — end-to-end round
  latency, bytes moved per round by direction, and (in pserver.py)
  bucket fill/size histograms — so a Prometheus dump shows whether
  buckets actually fill and rounds actually overlap.

Wire compatibility is the client's job: a `VariableClient` whose server
answers ERR to a batch verb falls back to per-var frames permanently
for that endpoint (see pserver.py), so one `CommPool` can serve mixed
old/new pserver fleets.

**Elastic clusters** (cloud/cluster.py, docs/resilience.md "Elastic
clusters"): when a cluster subscription is armed (`set_cluster` or the
``PADDLE_TPU_CONTROLLER`` env var), `elastic_round` re-derives each
round's endpoint map from the controller's current epoch-numbered view
instead of the transpile-time epmap, and a round that dies mid-flight
(SIGKILLed pserver, shard migrated away between view fetch and GET)
waits for the next stable view and retries against the new placement —
no process restart.  The transpiled epmap stays as the static fallback
for vars the view does not place.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

from ..observability import attribution as obs_attr
from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing
from .pserver import VariableClient

__all__ = ["CommPool", "comm_pool", "reset_comm_pool", "set_cluster",
           "get_cluster", "reset_cluster", "elastic_round"]

_LOG = logging.getLogger("paddle_tpu.comm")

# 64 B .. 1 GiB, x4 steps — grad rounds span tiny RNN cells to
# full embedding tables
_BYTE_BUCKETS = tuple(float(1 << i) for i in range(6, 31, 2))

_M_ROUND_SECONDS = obs_metrics.histogram(
    "paddle_tpu_comm_round_seconds",
    "end-to-end pserver round latency: bucketed sends + barrier + "
    "param pull across all endpoints (send/recv op)")
_M_ROUND_BYTES = obs_metrics.histogram(
    "paddle_tpu_comm_round_bytes",
    "serialized payload bytes moved per round, by direction (frame "
    "heads excluded so the directions are comparable)",
    ("direction",), buckets=_BYTE_BUCKETS)
_M_ROUND_RETRIES = obs_metrics.counter(
    "paddle_tpu_comm_round_retries_total",
    "elastic rounds retried against a fresh cluster view after a "
    "mid-round failure (dead pserver / migrated shard)")
# per-endpoint round attribution: the straggler detector
# (observability/attribution.py) compares endpoints' mean round time,
# so one slow pserver shows up as a z-score instead of hiding inside
# the all-endpoint round histogram
_M_EP_ROUND = obs_metrics.histogram(
    obs_attr.ENDPOINT_ROUND_METRIC,
    "per-endpoint slice of a fused round: sends + barrier + pull on "
    "that endpoint's worker (straggler attribution)",
    ("endpoint",))


def _default_client(endpoint: str) -> VariableClient:
    """Pool client factory.  Under an elastic cluster subscription the
    retry budget is deliberately SHORT (env-tunable via
    PADDLE_TPU_ELASTIC_RETRY_*): a dead pserver is not coming back on
    this endpoint — the recovery path is failing the round fast and
    replaying it against the controller's next view, not sitting in a
    multi-minute reconnect loop."""
    if get_cluster() is None:
        return VariableClient(endpoint)
    from ..core.resilience import RetryPolicy

    return VariableClient(
        endpoint, connect_timeout=2.0, request_timeout=15.0,
        barrier_timeout=15.0,
        retry_policy=RetryPolicy.from_env(
            "ELASTIC_RETRY", max_attempts=2, base_delay=0.05,
            max_delay=0.25, deadline=2.0))


class CommPool:
    """Per-endpoint connection + worker pool.

    One `VariableClient` and one single-thread executor per endpoint:
    within an endpoint requests stay ordered (sends must precede the
    barrier, the barrier must precede the pull), across endpoints they
    overlap — the serial `for ep in endpoints` loop the send op used to
    run paid one full round trip chain per pserver."""

    def __init__(self, client_factory=None):
        self._factory = client_factory or _default_client
        self._clients: Dict[str, VariableClient] = {}
        self._workers: Dict[str, ThreadPoolExecutor] = {}
        self._lock = threading.Lock()
        self._closed = False

    def client(self, endpoint: str) -> VariableClient:
        with self._lock:
            c = self._clients.get(endpoint)
            closed = self._closed
        if c is not None:
            # existing clients keep serving while close() drains the
            # workers — only NEW connections are refused, so an
            # in-flight round finishes instead of failing mid-round
            return c
        if closed:
            raise RuntimeError("CommPool is closed")
        # connect OUTSIDE the lock: a booting pserver can take
        # seconds, and other endpoints' clients must not wait on it
        c = self._factory(endpoint)
        with self._lock:
            if self._closed:
                extant = None
            else:
                extant = self._clients.setdefault(endpoint, c)
        if extant is None:
            c.close()
            raise RuntimeError("CommPool is closed")
        if extant is not c:
            c.close()
            c = extant
        return c

    def _worker(self, endpoint: str) -> ThreadPoolExecutor:
        with self._lock:
            if self._closed:
                raise RuntimeError("CommPool is closed")
            w = self._workers.get(endpoint)
            if w is None:
                w = ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix=f"comm-{endpoint}")
                self._workers[endpoint] = w
            return w

    def send_round(self, send_items: Sequence[Tuple[str, str, object]],
                   get_items: Sequence[Tuple[str, str]],
                   bucket_bytes: Optional[int] = None) -> List[object]:
        """One fused synchronous round.

        ``send_items``: [(endpoint, name, value)] grads in arrival
        order; ``get_items``: [(endpoint, name)] params to pull.  Per
        endpoint that received grads: bucketed sends, then the batch
        barrier, then one batched GET — chained on that endpoint's
        worker so endpoints overlap.  Endpoints appearing only in
        ``get_items`` are read without a barrier (recv-op semantics).
        Returns pulled values aligned with ``get_items``."""
        t0 = time.perf_counter()
        sends: Dict[str, list] = {}
        for ep, name, value in send_items:
            sends.setdefault(ep, []).append((name, value))
        gets: Dict[str, list] = {}
        for ep, name in get_items:
            gets.setdefault(ep, []).append(name)
        ctx = obs_tracing.current_context()

        def run_ep(ep):
            c = self.client(ep)
            s0, r0 = c.bytes_sent, c.bytes_recv
            te0 = time.perf_counter()
            with obs_tracing.activate(ctx), \
                    obs_tracing.span("comm.endpoint_round", endpoint=ep):
                if ep in sends:
                    c.send_vars(sends[ep], bucket_bytes)
                    with obs_attr.phase("trainer", "barrier_wait"):
                        c.send_batch_barrier()
                if ep in gets:
                    with obs_attr.phase("trainer", "get"):
                        vals = c.get_vars(gets[ep], bucket_bytes)
                else:
                    vals = []
            _M_EP_ROUND.labels(endpoint=ep).observe(
                time.perf_counter() - te0)
            return vals, c.bytes_sent - s0, c.bytes_recv - r0

        eps = sorted(set(sends) | set(gets))
        results: Dict[str, tuple] = {}
        if eps:
            # ALWAYS go through the per-endpoint worker — even for one
            # endpoint: two caller threads sharing the pool would
            # otherwise interleave frames on the same non-thread-safe
            # client socket; the worker is what serializes them
            futs = {}
            submit_exc = None
            for ep in eps:
                try:
                    futs[ep] = self._worker(ep).submit(run_ep, ep)
                except BaseException as e:
                    # pool closed mid-loop: stop submitting, but still
                    # drain what IS in flight below
                    submit_exc = e
                    break
            first_exc = None
            for ep, f in futs.items():
                # drain EVERY submitted future before raising: an
                # abandoned in-flight worker would race the caller's
                # error handling on the shared clients
                try:
                    results[ep] = f.result()
                except BaseException as e:
                    if first_exc is None:
                        first_exc = e
            if first_exc is None:
                first_exc = submit_exc
            if first_exc is not None:
                raise first_exc
        out, idx = [], {ep: 0 for ep in gets}
        for ep, name in get_items:
            out.append(results[ep][0][idx[ep]])
            idx[ep] += 1
        dt = time.perf_counter() - t0
        _M_ROUND_SECONDS.observe(dt)
        obs_attr.observe_phase("trainer", "send_round", dt)
        _M_ROUND_BYTES.labels(direction="sent").observe(
            sum(r[1] for r in results.values()))
        _M_ROUND_BYTES.labels(direction="recv").observe(
            sum(r[2] for r in results.values()))
        return out

    def forget(self, endpoint: str):
        """Drop the pooled client/worker for one endpoint so the next
        round reconnects fresh — the elastic retry path calls this for
        every endpoint a failed round touched (a dead pserver's socket
        must not be reused, and a survivor's batch-capability probe is
        cheap to redo)."""
        with self._lock:
            c = self._clients.pop(endpoint, None)
            w = self._workers.pop(endpoint, None)
        # a forgotten endpoint must not export a stale straggler
        # series forever (elastic churn)
        _M_EP_ROUND.remove(endpoint=endpoint)
        # the failed round drained every submitted future before
        # raising, so the worker is idle here
        if w is not None:
            w.shutdown(wait=False)
        if c is not None:
            try:
                c.close()
            except Exception:
                pass

    def close(self):
        # order matters: mark closed (new rounds and NEW connections
        # fail fast; existing clients keep serving), drain the workers
        # so in-flight rounds finish against those live clients, and
        # only then close the sockets — closing first would let a
        # draining round register a fresh connection into an
        # already-swept pool and leak it
        with self._lock:
            self._closed = True
            workers = list(self._workers.values())
            self._workers.clear()
        for w in workers:
            w.shutdown(wait=True)
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()


_POOL: Optional[CommPool] = None
_POOL_LOCK = threading.Lock()


def comm_pool() -> CommPool:
    """The process-wide pool the send/recv ops route through."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = CommPool()
        return _POOL


def reset_comm_pool():
    """Close every pooled connection/worker (tests, cluster teardown)."""
    global _POOL
    with _POOL_LOCK:
        pool, _POOL = _POOL, None
    if pool is not None:
        pool.close()


# ---------------------------------------------------------------------------
# elastic cluster subscription (cloud/cluster.py views)
# ---------------------------------------------------------------------------

_CLUSTER = None
_CLUSTER_TRIED_ENV = False


def set_cluster(cluster):
    """Arm the process-wide cluster subscription: `cluster` is a
    cloud.cluster.ClusterClient, a ClusterController (in-process
    clusters/tests), or a controller address string.  The send/recv ops
    then route every round through `elastic_round`."""
    global _CLUSTER, _CLUSTER_TRIED_ENV
    from ..cloud.cluster import ClusterClient, ClusterController

    if cluster is None or isinstance(cluster, ClusterClient):
        pass
    elif isinstance(cluster, (str, ClusterController)):
        cluster = ClusterClient(cluster)
    else:
        raise TypeError(f"set_cluster: expected ClusterClient, "
                        f"ClusterController or address, got {cluster!r}")
    with _POOL_LOCK:
        _CLUSTER = cluster
        _CLUSTER_TRIED_ENV = True
    return cluster


def get_cluster():
    """The armed cluster subscription, building one from the
    ``PADDLE_TPU_CONTROLLER`` env var on first call; None when the
    process is not part of an elastic cluster."""
    global _CLUSTER, _CLUSTER_TRIED_ENV
    with _POOL_LOCK:
        if _CLUSTER is not None or _CLUSTER_TRIED_ENV:
            return _CLUSTER
    # build OUTSIDE the lock (imports + construction), publish under
    # it: TRIED_ENV flips only together with the client so a
    # concurrent first caller can never observe "tried, but None" and
    # silently fall back to the static epmap for its round
    client = None
    addr = os.environ.get("PADDLE_TPU_CONTROLLER", "").strip()
    if addr:
        from ..cloud.cluster import ClusterClient

        client = ClusterClient(addr)
    with _POOL_LOCK:
        if _CLUSTER is None and not _CLUSTER_TRIED_ENV:
            _CLUSTER_TRIED_ENV = True
            _CLUSTER = client
        return _CLUSTER


def reset_cluster():
    """Drop the cluster subscription (tests, teardown).  The env var is
    re-read on the next get_cluster()."""
    global _CLUSTER, _CLUSTER_TRIED_ENV
    with _POOL_LOCK:
        c, _CLUSTER = _CLUSTER, None
        _CLUSTER_TRIED_ENV = False
    if c is not None:
        try:
            c.close()
        except Exception:
            pass


def _elastic_wait_s() -> float:
    try:
        return float(os.environ.get("PADDLE_TPU_ELASTIC_WAIT_S", "60"))
    except ValueError:
        return 60.0


def ensure_param_provider(scope):
    """Arm trainer-held shard recovery on the cluster subscription: the
    data-path scope's parameter copies (refreshed by every round's
    pull) become a recovery source when a pserver dies snapshotless.
    First scope wins; later calls with the same scope are no-ops."""
    import numpy as np

    cluster = get_cluster()
    if cluster is None or getattr(cluster, "_provider", None) is not None:
        return

    def provider(name):
        v = scope.find_var(name) if scope.has_var(name) else None
        if v is None:
            return None
        try:
            return np.asarray(v)
        except Exception:
            return v  # LoDTensor/SelectedRows ship as-is

    cluster.set_param_provider(provider)


def elastic_round(sends, gets, bucket_bytes: Optional[int] = None,
                  scope=None) -> List[object]:
    """One send/recv round that survives membership changes.

    ``sends``: [(placement_key, wire_name, value, fallback_ep)] — the
    placement key is the PARAM name (cluster views place params; grads
    ride to their param's owner), the wire name is what the pserver
    stores (the grad name).  ``gets``: [(placement_key, wire_name,
    fallback_ep)].  Without a cluster subscription this is exactly
    CommPool.send_round over the fallback endpoints.

    With one, each attempt maps keys through the CURRENT stable view's
    placement and a failed attempt (dead pserver: retries exhausted
    below; stale placement: the server's ERR for an unknown var) forgets
    the touched connections, waits for a FRESH stable view (the
    controller publishes one once the dead member's TTL lease expires
    and shards have migrated), and replays the whole round against the
    new placement.  Replaying a round that half-applied is safe:
    re-sent grads overwrite this trainer's per-trainer slot, and a
    round the commit released early is simply lost — at-least-once
    sync SGD (docs/resilience.md)."""
    from .pserver import BarrierTimeoutError

    pool = comm_pool()
    cluster = get_cluster()
    if cluster is None:
        return pool.send_round(
            [(ep, n, v) for _, n, v, ep in sends],
            [(ep, n) for _, n, ep in gets], bucket_bytes)
    if scope is not None:
        ensure_param_provider(scope)
    wait_s = _elastic_wait_s()
    last_exc = None
    for attempt in range(8):
        view = cluster.ready_view(timeout_s=wait_s)
        place = view.placement
        send_items = [(place.get(k, ep), n, v) for k, n, v, ep in sends]
        get_items = [(place.get(k, ep), n) for k, n, ep in gets]
        try:
            return pool.send_round(send_items, get_items, bucket_bytes)
        except (OSError, ConnectionError, RuntimeError,
                BarrierTimeoutError) as e:
            last_exc = e
            touched = {ep for ep, _, _ in send_items} | \
                      {ep for ep, _ in get_items}
            for ep in touched:
                pool.forget(ep)
            _M_ROUND_RETRIES.inc()
            _LOG.warning(
                "elastic round failed under view %d (%s); waiting for "
                "a fresh cluster view", view.epoch, e)
            # wait briefly for a NEWER view (the usual cause: a member
            # died and the controller is rebalancing).  If none comes,
            # the failure was transient — a barrier timed out on
            # round-skew, a peer was mid-replay — so replay against
            # the CURRENT view; the attempt cap bounds the total spin.
            nxt = cluster.wait_view(view.epoch + 1,
                                    timeout_s=min(wait_s, 5.0))
            if nxt is None:
                _LOG.warning(
                    "elastic round: no newer view than %d; replaying "
                    "against the current placement", view.epoch)
    raise last_exc
