"""Composed dp x pp x tp training step: ZeRO-1 + gradient accumulation.

The configuration a real pod runs is not one parallelism axis but their
product: batch sharded over 'dp', the layer stack split over 'pp'
(GPipe, parallel/pipeline.py), each stage's matmuls Megatron-split over
'tp' (column-parallel in, row-parallel out, one psum), momentum state
sharded over 'dp' (ZeRO-1), and gradients accumulated over A micro-steps
inside one compiled program (lax.scan) before the update.  The reference
composes the analogous axes across separate subsystems
(MultiGradientMachine dp x ParallelNeuralNetwork per-layer placement x
sharded pservers); here the whole composition is ONE jitted SPMD program
and XLA inserts the collectives.

`make_composite_step` returns (step_fn, params, velocity) with every
array already placed under its NamedSharding; `step_fn(params, velocity,
batches)` -> (new_params, new_velocity, mean_loss) is jit-compiled with
donated state.  `collective_counts` digests the optimized HLO so tests /
dryruns can pin the communication structure (ppermute hops + grad
all-reduce + tp psum must all be present).
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import count_collectives
from .pipeline import (microbatch, spmd_pipeline, stack_stage_params,
                       unmicrobatch)

__all__ = ["make_composite_step", "collective_counts"]


def _stage_fn(params, h):
    """One Megatron-split MLP stage under shard_map: w1 column-parallel
    (local [D, H/tp], no comm), w2 row-parallel (local [H/tp, D], one
    psum over 'tp')."""
    w1, b1, w2, b2 = params
    u = jnp.tanh(h @ w1 + b1)
    return jax.lax.psum(u @ w2, "tp") + b2


def make_composite_step(mesh: Mesh, dim: int = 8, hidden: int = 16,
                        n_micro: int = 4,
                        lr: float = 0.05, mu: float = 0.9, seed: int = 0):
    """Build the composed step over `mesh` (axes 'dp', 'pp', 'tp').

    Shardings:
      params   w1 [pp, D, H] P('pp', None, 'tp')   (stage x column-split)
               w2 [pp, H, D] P('pp', 'tp', None)   (stage x row-split)
               b1 [pp, H]    P('pp', 'tp')
               b2 [pp, D]    P('pp')
      velocity same as params PLUS the free dim sharded over 'dp'
               (ZeRO-1: each dp replica owns a slice of optimizer state)
    """
    pp = mesh.shape["pp"]
    dp = mesh.shape["dp"]
    tp = mesh.shape["tp"]
    # the ZeRO-1 velocity specs shard dim over dp and hidden over tp*dp
    # (and the param specs shard hidden over tp); grow the demo sizes to
    # the next multiple so ANY mesh shape places cleanly
    lcm = np.lcm
    dim = int(lcm(dim, dp))
    hidden = int(lcm(hidden, tp * dp))
    r = np.random.RandomState(seed)
    per_stage = [(jnp.asarray(r.randn(dim, hidden), jnp.float32) * 0.3,
                  jnp.zeros((hidden,), jnp.float32),
                  jnp.asarray(r.randn(hidden, dim), jnp.float32) * 0.3,
                  jnp.zeros((dim,), jnp.float32)) for _ in range(pp)]
    params = stack_stage_params(per_stage)
    p_specs = (P("pp", None, "tp"), P("pp", "tp"),
               P("pp", "tp", None), P("pp"))
    v_specs = (P("pp", "dp", "tp"), P("pp", ("tp", "dp")),
               P("pp", "tp", "dp"), P("pp", "dp"))
    params = tuple(jax.device_put(x, NamedSharding(mesh, s))
                   for x, s in zip(params, p_specs))
    velocity = tuple(jax.device_put(jnp.zeros_like(x),
                                    NamedSharding(mesh, s))
                     for x, s in zip(params, v_specs))

    def loss_fn(p, xb, yb):
        out = spmd_pipeline(_stage_fn, p, microbatch(xb, n_micro), mesh,
                            batch_axis="dp", param_specs=p_specs)
        return jnp.mean((unmicrobatch(out) - yb) ** 2)

    def step(params, velocity, xs, ys):
        """xs/ys: [accum, batch, dim] — grads accumulate over the leading
        axis inside the compiled program, then one momentum update.  The
        accumulation count is xs' leading dim (static at trace time), so
        the mean is correct for whatever depth the caller feeds."""
        if xs.shape[-1] != dim or ys.shape[-1] != dim:
            # dim/hidden are grown to lcm multiples above so ANY mesh
            # places cleanly — callers must size data to the EFFECTIVE
            # dim (read it from params: w1 is [pp, dim, hidden])
            raise ValueError(
                f"data feature dim {xs.shape[-1]}/{ys.shape[-1]} != "
                f"effective model dim {dim} (requested dim grew to "
                f"lcm(dim, dp) for this mesh; size inputs from "
                "params[0].shape[1])")
        n_acc = xs.shape[0]

        def acc(carry, xy):
            g_acc, l_acc = carry
            xb, yb = xy
            l, g = jax.value_and_grad(loss_fn)(params, xb, yb)
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
            return (g_acc, l_acc + l), None

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        (g, loss_sum), _ = jax.lax.scan(acc, (zeros, 0.0), (xs, ys))
        g = jax.tree_util.tree_map(lambda v: v / n_acc, g)
        new_v = jax.tree_util.tree_map(lambda v, gg: mu * v + gg,
                                       velocity, g)
        new_p = jax.tree_util.tree_map(lambda p, v: p - lr * v,
                                       params, new_v)
        return new_p, new_v, loss_sum / n_acc

    sh = lambda specs: tuple(NamedSharding(mesh, s) for s in specs)
    data_sh = NamedSharding(mesh, P(None, "dp"))
    step_fn = jax.jit(
        step,
        in_shardings=(sh(p_specs), sh(v_specs), data_sh, data_sh),
        out_shardings=(sh(p_specs), sh(v_specs), None),
        donate_argnums=(0, 1),
    )
    return step_fn, params, velocity


def collective_counts(step_fn, *args) -> Dict[str, int]:
    """Counts of collective ops in the optimized HLO for `args`' avals —
    pins that the composition really communicates as designed
    (collective-permute = pipeline hops, all-reduce = dp grad sum + tp
    psum, reduce-scatter/all-gather = ZeRO-1 state resharding)."""
    txt = step_fn.lower(*args).compile().as_text()
    return count_collectives(txt)
