"""Composed dp x pp x tp training step: ZeRO-1 + gradient accumulation.

STATUS (r5): the raw-jax TEST ORACLE for the composed mesh.  The
production path is `parallel.PipelineExecutor(tp_axis=..., sp_axis=...,
schedule=...)`, which runs the USER'S fluid.layers Program under the
same composition (pipeline_program.py; pinned against serial in
tests/test_pipeline_tp.py and tests/test_1f1b.py).  This module's
hand-built models remain the independent twin those tests and the
dryrun compare collective structure against.

The configuration a real pod runs is not one parallelism axis but their
product: batch sharded over 'dp', the layer stack split over 'pp'
(GPipe, parallel/pipeline.py), each stage's matmuls Megatron-split over
'tp' (column-parallel in, row-parallel out, one psum), momentum state
sharded over 'dp' (ZeRO-1), and gradients accumulated over A micro-steps
inside one compiled program (lax.scan) before the update.  The reference
composes the analogous axes across separate subsystems
(MultiGradientMachine dp x ParallelNeuralNetwork per-layer placement x
sharded pservers); here the whole composition is ONE jitted SPMD program
and XLA inserts the collectives.

`make_composite_step` returns (step_fn, params, velocity) with every
array already placed under its NamedSharding; `step_fn(params, velocity,
batches)` -> (new_params, new_velocity, mean_loss) is jit-compiled with
donated state.  `collective_counts` digests the optimized HLO so tests /
dryruns can pin the communication structure (ppermute hops + grad
all-reduce + tp psum must all be present).
"""
from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import count_collectives
from .pipeline import (microbatch, spmd_pipeline, stack_stage_params,
                       unmicrobatch)

__all__ = ["make_composite_step", "make_transformer_composite_step",
           "collective_counts"]


def _stage_fn(params, h):
    """One Megatron-split MLP stage under shard_map: w1 column-parallel
    (local [D, H/tp], no comm), w2 row-parallel (local [H/tp, D], one
    psum over 'tp')."""
    w1, b1, w2, b2 = params
    u = jnp.tanh(h @ w1 + b1)
    return jax.lax.psum(u @ w2, "tp") + b2


def make_composite_step(mesh: Mesh, dim: int = 8, hidden: int = 16,
                        n_micro: int = 4,
                        lr: float = 0.05, mu: float = 0.9, seed: int = 0):
    """Build the composed step over `mesh` (axes 'dp', 'pp', 'tp').

    Shardings:
      params   w1 [pp, D, H] P('pp', None, 'tp')   (stage x column-split)
               w2 [pp, H, D] P('pp', 'tp', None)   (stage x row-split)
               b1 [pp, H]    P('pp', 'tp')
               b2 [pp, D]    P('pp')
      velocity same as params PLUS the free dim sharded over 'dp'
               (ZeRO-1: each dp replica owns a slice of optimizer state)
    """
    pp = mesh.shape["pp"]
    dp = mesh.shape["dp"]
    tp = mesh.shape["tp"]
    # the ZeRO-1 velocity specs shard dim over dp and hidden over tp*dp
    # (and the param specs shard hidden over tp); grow the demo sizes to
    # the next multiple so ANY mesh shape places cleanly
    lcm = np.lcm
    dim = int(lcm(dim, dp))
    hidden = int(lcm(hidden, tp * dp))
    r = np.random.RandomState(seed)
    per_stage = [(jnp.asarray(r.randn(dim, hidden), jnp.float32) * 0.3,
                  jnp.zeros((hidden,), jnp.float32),
                  jnp.asarray(r.randn(hidden, dim), jnp.float32) * 0.3,
                  jnp.zeros((dim,), jnp.float32)) for _ in range(pp)]
    params = stack_stage_params(per_stage)
    p_specs = (P("pp", None, "tp"), P("pp", "tp"),
               P("pp", "tp", None), P("pp"))
    v_specs = (P("pp", "dp", "tp"), P("pp", ("tp", "dp")),
               P("pp", "tp", "dp"), P("pp", "dp"))
    params = tuple(jax.device_put(x, NamedSharding(mesh, s))
                   for x, s in zip(params, p_specs))
    velocity = tuple(jax.device_put(jnp.zeros_like(x),
                                    NamedSharding(mesh, s))
                     for x, s in zip(params, v_specs))

    def loss_fn(p, xb, yb):
        out = spmd_pipeline(_stage_fn, p, microbatch(xb, n_micro), mesh,
                            batch_axis="dp", param_specs=p_specs)
        return jnp.mean((unmicrobatch(out) - yb) ** 2)

    def step(params, velocity, xs, ys):
        """xs/ys: [accum, batch, dim] — grads accumulate over the leading
        axis inside the compiled program, then one momentum update.  The
        accumulation count is xs' leading dim (static at trace time), so
        the mean is correct for whatever depth the caller feeds."""
        if xs.shape[-1] != dim or ys.shape[-1] != dim:
            # dim/hidden are grown to lcm multiples above so ANY mesh
            # places cleanly — callers must size data to the EFFECTIVE
            # dim (read it from params: w1 is [pp, dim, hidden])
            raise ValueError(
                f"data feature dim {xs.shape[-1]}/{ys.shape[-1]} != "
                f"effective model dim {dim} (requested dim grew to "
                f"lcm(dim, dp) for this mesh; size inputs from "
                "params[0].shape[1])")
        n_acc = xs.shape[0]

        def acc(carry, xy):
            g_acc, l_acc = carry
            xb, yb = xy
            l, g = jax.value_and_grad(loss_fn)(params, xb, yb)
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
            return (g_acc, l_acc + l), None

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        (g, loss_sum), _ = jax.lax.scan(acc, (zeros, 0.0), (xs, ys))
        g = jax.tree_util.tree_map(lambda v: v / n_acc, g)
        new_v = jax.tree_util.tree_map(lambda v, gg: mu * v + gg,
                                       velocity, g)
        new_p = jax.tree_util.tree_map(lambda p, v: p - lr * v,
                                       params, new_v)
        return new_p, new_v, loss_sum / n_acc

    sh = lambda specs: tuple(NamedSharding(mesh, s) for s in specs)
    data_sh = NamedSharding(mesh, P(None, "dp"))
    step_fn = jax.jit(
        step,
        in_shardings=(sh(p_specs), sh(v_specs), data_sh, data_sh),
        out_shardings=(sh(p_specs), sh(v_specs), None),
        donate_argnums=(0, 1),
    )
    return step_fn, params, velocity


def _tfm_stage_fn(params, h, *, d_head):
    """One pre-LN transformer block as a pipeline stage under shard_map,
    Megatron-split over 'tp' (the real-model counterpart of the MLP demo
    above — VERDICT r3 weak #1).

    Local views (the 'tp' axis is in scope inside spmd_pipeline's
    shard_map): wq/wk/wv [D, D/tp] column-parallel (a contiguous block of
    n_heads/tp heads, no comm), wo [D/tp, D] row-parallel (one psum);
    w1 [D, H/tp] column + w2 [H/tp, D] row (one psum).  LayerNorm runs on
    the full feature dim, which stays replicated across tp between
    sublayers.  h: [mb, S, D].
    """
    (ls1, lb1, wq, wk, wv, wo, bo, ls2, lb2, w1, b1, w2, b2) = params

    def ln(x, s, b):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * s + b

    from ..kernels.flash_attention import flash_attention

    mb, S, D = h.shape
    d_loc = wq.shape[1]                      # D/tp columns = local heads
    hx = ln(h, ls1, lb1)
    q = (hx @ wq).reshape(mb, S, d_loc // d_head, d_head)
    k = (hx @ wk).reshape(mb, S, d_loc // d_head, d_head)
    v = (hx @ wv).reshape(mb, S, d_loc // d_head, d_head)
    att = flash_attention(q, k, v, causal=True)
    att = att.reshape(mb, S, d_loc)
    h = h + jax.lax.psum(att @ wo, "tp") + bo
    hx = ln(h, ls2, lb2)
    u = jnp.maximum(hx @ w1 + b1, 0.0)
    return h + jax.lax.psum(u @ w2, "tp") + b2


def make_transformer_composite_step(mesh: Mesh, vocab: int = 32,
                                    n_heads: int = 4, d_head: int = 8,
                                    seq: int = 8, n_micro: int = 2,
                                    lr: float = 0.2, mu: float = 0.9,
                                    seed: int = 0):
    """The composed dp x pp x tp step on a REAL model: a causal
    transformer LM whose block stack is the pipelined trunk (one block
    per 'pp' device), attention/FFN projections Megatron-split over
    'tp', embedding + classifier outside the trunk (the usual GPipe
    decomposition), ZeRO-1 momentum sharding over 'dp', and in-program
    gradient accumulation.  The reference's matching discipline is
    running the real VGG-16 through its distributed machinery
    (/root/reference/benchmark/cluster/vgg16/vgg16_fluid.py), not a toy.

    Returns (step_fn, params, velocity, meta) — meta carries the
    effective sizes {vocab, d_model, seq, n_heads} so callers can size
    id batches for any mesh.  step_fn(params, velocity, ids, labels)
    with ids/labels [accum, batch, seq] int32 -> (new_params,
    new_velocity, mean_loss).
    """
    pp = mesh.shape["pp"]
    dp = mesh.shape["dp"]
    tp = mesh.shape["tp"]
    lcm = np.lcm
    # d_model must divide by tp (column split) AND the ZeRO-1 velocity
    # specs shard d_model/d_ffn dims over dp (and tp*dp jointly for b1),
    # so grow the head count until d_model is a tp*dp multiple
    base_width = n_heads * d_head
    n_heads = int(lcm(n_heads, tp * dp))
    d_model = n_heads * d_head
    # the lcm growth widens the model with the mesh (dp=8 tp=2 ->
    # d_model 128 vs the base 32); a fixed lr that trains the base
    # width diverges at 4x it (observed at 32 virtual devices), so
    # scale 1/width (muP hidden-lr rule) — exactly neutral at base
    lr = lr * base_width / d_model
    d_ffn = 4 * d_model
    vocab = int(lcm(vocab, dp))
    stage_fn = functools.partial(_tfm_stage_fn, d_head=d_head)
    r = np.random.RandomState(seed)

    def rnd(*shape, s=0.05):
        return jnp.asarray(r.randn(*shape), jnp.float32) * s

    per_stage = [
        (jnp.ones((d_model,), jnp.float32), jnp.zeros((d_model,)),
         rnd(d_model, d_model), rnd(d_model, d_model),
         rnd(d_model, d_model), rnd(d_model, d_model),
         jnp.zeros((d_model,)),
         jnp.ones((d_model,), jnp.float32), jnp.zeros((d_model,)),
         rnd(d_model, d_ffn), jnp.zeros((d_ffn,)),
         rnd(d_ffn, d_model), jnp.zeros((d_model,)))
        for _ in range(pp)]
    stack = stack_stage_params(per_stage)
    p_specs = (P("pp"), P("pp"),                       # ln1
               P("pp", None, "tp"), P("pp", None, "tp"),
               P("pp", None, "tp"),                    # wq wk wv (col)
               P("pp", "tp", None), P("pp"),           # wo (row), bo
               P("pp"), P("pp"),                       # ln2
               P("pp", None, "tp"), P("pp", "tp"),     # w1 (col), b1
               P("pp", "tp", None), P("pp"))           # w2 (row), b2
    # ZeRO-1: velocity additionally shards a free dim over 'dp'
    v_specs = (P("pp", "dp"), P("pp", "dp"),
               P("pp", "dp", "tp"), P("pp", "dp", "tp"),
               P("pp", "dp", "tp"),
               P("pp", "tp", "dp"), P("pp", "dp"),
               P("pp", "dp"), P("pp", "dp"),
               P("pp", "dp", "tp"), P("pp", ("tp", "dp")),
               P("pp", "tp", "dp"), P("pp", "dp"))
    outer = {
        "emb": rnd(vocab, d_model, s=0.1),
        "pos": rnd(seq, d_model, s=0.1),
        # fan-in scale: with the final standardize in loss_fn this keeps
        # logits O(1) at ANY lcm-grown width (a fixed scale made the 64-
        # device d_model-256 step start above uniform loss and diverge)
        "cls_w": rnd(d_model, vocab, s=float(d_model) ** -0.5),
        "cls_b": jnp.zeros((vocab,), jnp.float32),
    }
    o_specs = {"emb": P(None), "pos": P(), "cls_w": P(), "cls_b": P()}
    ov_specs = {"emb": P("dp"), "pos": P(), "cls_w": P("dp"),
                "cls_b": P("dp")}

    stack = tuple(jax.device_put(x, NamedSharding(mesh, s))
                  for x, s in zip(stack, p_specs))
    outer = {k: jax.device_put(v, NamedSharding(mesh, o_specs[k]))
             for k, v in outer.items()}
    params = (outer, stack)
    velocity = (
        {k: jax.device_put(jnp.zeros_like(outer[k]),
                           NamedSharding(mesh, ov_specs[k]))
         for k in outer},
        tuple(jax.device_put(jnp.zeros_like(x), NamedSharding(mesh, s))
              for x, s in zip(stack, v_specs)))

    def loss_fn(p, ids, labels):
        o, st = p
        x = o["emb"][ids] + o["pos"][None, :, :]        # [B, S, D]
        x = microbatch(x, n_micro)
        x = spmd_pipeline(stage_fn, st, x, mesh, batch_axis="dp",
                          param_specs=p_specs)
        x = unmicrobatch(x)
        # parameterless final norm (pre-LN convention): the residual
        # stream's magnitude grows with depth/width, and an unnormalized
        # classifier input is what made the widest meshes diverge
        x = (x - x.mean(-1, keepdims=True)) / jnp.sqrt(
            x.var(-1, keepdims=True) + 1e-6)
        logits = x @ o["cls_w"] + o["cls_b"]            # [B, S, V]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None],
                                   axis=-1)[..., 0]
        return jnp.mean(nll)

    def step(params, velocity, ids, labels):
        n_acc = ids.shape[0]

        def acc(carry, batch):
            g_acc, l_acc = carry
            ib, lb = batch
            l, g = jax.value_and_grad(loss_fn)(params, ib, lb)
            return (jax.tree_util.tree_map(jnp.add, g_acc, g),
                    l_acc + l), None

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        (g, loss_sum), _ = jax.lax.scan(acc, (zeros, 0.0), (ids, labels))
        g = jax.tree_util.tree_map(lambda v: v / n_acc, g)
        new_v = jax.tree_util.tree_map(lambda v, gg: mu * v + gg,
                                       velocity, g)
        new_p = jax.tree_util.tree_map(lambda p, v: p - lr * v,
                                       params, new_v)
        return new_p, new_v, loss_sum / n_acc

    sh = lambda specs: tuple(NamedSharding(mesh, s) for s in specs)
    osh = lambda specs: {k: NamedSharding(mesh, s)
                         for k, s in specs.items()}
    p_sh = (osh(o_specs), sh(p_specs))
    v_sh = (osh(ov_specs), sh(v_specs))
    data_sh = NamedSharding(mesh, P(None, "dp"))
    step_fn = jax.jit(
        step,
        in_shardings=(p_sh, v_sh, data_sh, data_sh),
        out_shardings=(p_sh, v_sh, None),
        donate_argnums=(0, 1),
    )
    meta = {"vocab": vocab, "d_model": d_model, "seq": seq,
            "n_heads": n_heads}
    return step_fn, params, velocity, meta


def collective_counts(step_fn, *args) -> Dict[str, int]:
    """Counts of collective ops in the optimized HLO for `args`' avals —
    pins that the composition really communicates as designed
    (collective-permute = pipeline hops, all-reduce = dp grad sum + tp
    psum, reduce-scatter/all-gather = ZeRO-1 state resharding)."""
    txt = step_fn.lower(*args).compile().as_text()
    return count_collectives(txt)
