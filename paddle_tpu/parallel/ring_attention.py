"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has NO attention kernel or sequence parallelism (SURVEY.md
§5.7: long sequences are handled by LoD bucketing + dynamic RNN); this is
the TPU-native long-context capability the rebuild adds as first-class:
queries stay resident per shard while key/value blocks rotate around the
ring via `ppermute` (one ICI hop per step), accumulating streaming-softmax
(flash-style) partial results — memory O(seq/N) per chip, compute fully
overlapped with neighbor transfers by XLA's async collectives.

Also provides `all_to_all_attention` (DeepSpeed-Ulysses layout): heads
scatter / sequence gather so each chip computes full-sequence attention for
a head subset — cheaper at moderate sequence lengths.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import shard_map

__all__ = ["ring_attention", "ring_attention_local",
           "all_to_all_attention", "attention_reference"]


def _block_attn(q, k, v, scale, causal, q_off, kv_off):
    """One (q-block, kv-block) tile: returns (unnormalized out, running max,
    running denom) for streaming softmax.

    Lowering note: the per-chunk scores here are XLA-composed (the
    [b, h, blk, blk] tile materializes in HBM).  Swapping in the Pallas
    flash kernel per chunk needs an (o, lse) partial contract WITH a
    custom VJP that propagates the lse cotangent through the ring merge
    — unverifiable on this 1-chip environment (the kernel only lowers
    on real TPU multi-chip meshes), so the composed form stays until a
    pod is available to validate it."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        ql = q.shape[1]
        kl = k.shape[1]
        qi = q_off + jnp.arange(ql)[:, None]
        ki = kv_off + jnp.arange(kl)[None, :]
        s = jnp.where(qi >= ki, s, -jnp.inf)
    m = jnp.max(s, axis=-1)                     # [b,h,q]
    # guard fully-masked rows
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    denom = jnp.sum(p, axis=-1)                 # [b,h,q]
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v)   # unnormalized
    return out, m_safe, denom


def _merge(acc, new):
    """Merge two streaming-softmax partials (flash-attention combine)."""
    out_a, m_a, d_a = acc
    out_n, m_n, d_n = new
    m = jnp.maximum(m_a, m_n)
    ca = jnp.exp(m_a - m)
    cn = jnp.exp(m_n - m)
    out = out_a * ca.transpose(0, 2, 1)[..., None] \
        + out_n * cn.transpose(0, 2, 1)[..., None]
    return out, m, d_a * ca + d_n * cn


def ring_attention_local(q_blk, k_blk, v_blk, axis: str, n: int,
                         causal: bool = False, scale: float = None):
    """The ring-attention BODY: call it inside an enclosing `shard_map`
    where `axis` (size `n`) is a manual mesh axis and q/k/v arrive as the
    LOCAL [batch, seq/n, heads, dim] sequence blocks.  Used by
    `ring_attention` below and by the flash_attention op lowering when a
    PipelineExecutor stage runs with sequence parallelism (sp composed
    with pp/dp/tp in one program)."""
    scale = scale if scale is not None else q_blk.shape[-1] ** -0.5
    blk = q_blk.shape[1]
    kv_blk = k_blk.shape[1]
    idx = jax.lax.axis_index(axis)
    q_off = idx * blk

    def body(i, carry):
        acc, k_cur, v_cur, src = carry
        kv_off = src * kv_blk
        new = _block_attn(q_blk, k_cur, v_cur, scale, causal,
                          q_off, kv_off)
        acc = _merge(acc, new)
        # rotate kv to the next ring position (one ICI hop)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return acc, k_nxt, v_nxt, (src - 1) % n

    # the initial carry must match the body's varying-manual-axes type
    # (the merge makes it vary over EVERY manual axis q varies over —
    # not just `axis`: under PipelineExecutor the enclosing shard_map is
    # also manual over dp/pp), so build the zeros FROM q_blk and let
    # them inherit its vma instead of pcast-ing a fixed axis list
    mvec = jnp.transpose(q_blk[..., 0], (0, 2, 1))       # [b, h, blk]
    acc0 = (jnp.zeros_like(q_blk),
            jnp.full_like(mvec, -jnp.inf),
            jnp.zeros_like(mvec))
    (out, m, denom), _, _, _ = jax.lax.fori_loop(
        0, n, body, (acc0, k_blk, v_blk, idx))
    denom = jnp.maximum(denom, 1e-20)
    return out / denom.transpose(0, 2, 1)[..., None]


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = False, scale: float = None):
    """Attention with sequence sharded over `axis`.

    q/k/v: [batch, seq, heads, dim] GLOBAL arrays (sharded or to-be-sharded
    on dim 1).  Returns the attention output with the same layout."""
    n = mesh.shape[axis]
    seq = q.shape[1]
    assert seq % n == 0, "seq length must divide the sp axis"

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, axis, None, None),) * 3,
        out_specs=P(None, axis, None, None))
    def _ring(q_blk, k_blk, v_blk):
        return ring_attention_local(q_blk, k_blk, v_blk, axis, n,
                                    causal=causal, scale=scale)

    return _ring(q, k, v)


def all_to_all_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                         causal: bool = False, scale: float = None):
    """Ulysses-style: all_to_all swaps the sharded dim from sequence to
    heads, full-sequence attention per head shard, swap back."""
    n = mesh.shape[axis]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    assert q.shape[2] % n == 0, "head count must divide the sp axis"

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, axis, None, None),) * 3,
        out_specs=P(None, axis, None, None))
    def _u(q_blk, k_blk, v_blk):
        def seq_to_heads(x):
            # [b, s/n, h, d] -> gather seq, scatter heads -> [b, s, h/n, d]
            x = jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                   tiled=True)
            return x
        def heads_to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)
        qh, kh, vh = seq_to_heads(q_blk), seq_to_heads(k_blk), \
            seq_to_heads(v_blk)
        out, m, denom = _block_attn(qh, kh, vh, scale, causal, 0, 0)
        out = out / jnp.maximum(denom, 1e-20).transpose(0, 2, 1)[..., None]
        return heads_to_seq(out)

    return _u(q, k, v)


def attention_reference(q, k, v, causal=False, scale=None):
    """Single-device reference for tests (one oracle for the whole tree:
    delegates to kernels.flash_attention_reference)."""
    from ..kernels.flash_attention import flash_attention_reference

    return flash_attention_reference(q, k, v, causal=causal, scale=scale)
