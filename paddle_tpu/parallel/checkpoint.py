"""Sharded-training checkpoint/restore for the parallel executors.

Reference: the Go pserver snapshotted its SHARD of the distributed state
with {uuid, md5, timestamp} meta and restored on restart
(/root/reference/go/pserver/service.go:120-203,346;
doc/design/cluster_train/checkpointing.md).  Here the executor holds the
whole mesh-sharded state as global jax Arrays, so the snapshot gathers
each state to one host array (placement-independent by construction) and
reuses io.py's meta/publish/GC protocol; restore re-places every array
under the CURRENT executor's shardings, so a run saved on a dp-8 mesh
restores onto dp-4 (or any mesh with the same logical axes sizes where
it matters — e.g. the pipeline stage count) with re-placement for free.
"""
from __future__ import annotations

import os
import uuid as uuid_mod

import numpy as np

import jax

STATES_FILENAME = "sharded_states.npz"


class ShardedCheckpointMixin:
    """Adds save_checkpoint/restore_checkpoint to an executor exposing
    `_states` (name -> global Array), `_state_shardings`, `_step`,
    and `mesh`."""

    def save_checkpoint(self, dirname, trainer_args=None,
                        max_keep: int = 3) -> str:
        """Gather the sharded training state (params + optimizer
        accumulators, incl. ZeRO-1 shards) to host and snapshot it under
        `dirname` with {uuid, md5, timestamp} meta.  Returns the uuid."""
        from .. import io as _io

        if jax.process_count() > 1:
            # multi-process SPMD: shards of a global Array live on other
            # processes (np.asarray would raise non-addressable) and
            # every process would race the __latest__ pointer.  The
            # multi-host story is per-process orbax-style sharding or
            # the pserver path's own snapshots — out of scope here.
            raise NotImplementedError(
                "save_checkpoint is single-controller: call it from a "
                "1-process run (multi-host saves need a gather + "
                "process-0 publish)")
        cp_uuid = uuid_mod.uuid4().hex
        cp_dir = os.path.join(dirname,
                              f"{_io.CHECKPOINT_PREFIX}_{cp_uuid}")
        os.makedirs(cp_dir, exist_ok=True)
        host = {n: np.asarray(v) for n, v in self._states.items()}
        np.savez(os.path.join(cp_dir, STATES_FILENAME), **host)
        args = dict(trainer_args or {})
        args.setdefault("step", self._step)
        args.setdefault("mesh_axes", dict(self.mesh.shape))
        _io.publish_checkpoint(dirname, cp_uuid, cp_dir, args, max_keep)
        return cp_uuid

    def restore_checkpoint(self, dirname):
        """Restore the latest valid (md5-verified) snapshot under
        `dirname` onto THIS executor's mesh — the saved arrays are
        global, so a different dp size just re-places them.  Restores
        the RNG step counter too.  Returns the snapshot meta, or None
        when no usable snapshot exists."""
        from .. import io as _io

        # the dir layout is shared with the serial io.save_checkpoint
        # protocol, so the latest valid snapshot may be a serial one
        # (persistables files, no sharded npz).  Mixed directories
        # happen (e.g. a serial warm-start save followed by sharded
        # training snapshots): restore the newest md5-valid snapshot
        # that DOES carry the sharded npz — warning loudly if that
        # skips a newer serial snapshot, since resuming from it rewinds
        # past whatever progress the serial save recorded.
        cp_dir, meta = _io.latest_checkpoint(
            dirname, require=lambda d: os.path.exists(
                os.path.join(d, STATES_FILENAME)))
        if cp_dir is None:
            if (not os.path.isdir(dirname)
                    or not _io._checkpoints_by_time(dirname)):
                return None  # empty/absent directory: documented contract
            raise RuntimeError(
                f"no snapshot under {dirname} carries {STATES_FILENAME} — "
                "it holds serial Executor saves only; restore those with "
                "io.load_checkpoint, or point ParallelExecutor at a "
                "directory of sharded snapshots")
        # cheap newer-serial detection: metadata timestamps only, no md5
        newer = [m for _, name, m in _io._checkpoints_by_time(dirname)
                 if m.get("timestamp", 0) > meta.get("timestamp", 0)
                 and not os.path.exists(os.path.join(
                     dirname, name, STATES_FILENAME))]
        if newer:
            import warnings

            warnings.warn(
                f"restore_checkpoint: newer snapshot {newer[-1]['uuid']} "
                f"has no {STATES_FILENAME} (serial save); resuming from "
                f"older sharded snapshot {meta['uuid']} — training state "
                "rewinds to it", RuntimeWarning, stacklevel=2)
        path = os.path.join(cp_dir, STATES_FILENAME)
        with np.load(path) as data:
            missing = sorted(set(self._states) - set(data.files))
            if missing:
                raise RuntimeError(
                    f"checkpoint {meta['uuid']} lacks state var(s) "
                    f"{missing} — was it saved from a different "
                    "program/strategy?")
            bad_shape = [
                (n, data[n].shape, tuple(self._states[n].shape))
                for n in self._states
                if tuple(data[n].shape) != tuple(self._states[n].shape)]
            if bad_shape:
                raise RuntimeError(
                    f"checkpoint {meta['uuid']} shape mismatch (saved vs "
                    f"current): {bad_shape} — same names, different "
                    "architecture?")
            self._states = {
                n: jax.device_put(data[n], self._state_shardings[n])
                for n in self._states
            }
        self._step = int(meta.get("trainer_args", {})
                         .get("step", self._step))
        return meta
