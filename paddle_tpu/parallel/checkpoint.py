"""Sharded-training checkpoint/restore for the parallel executors.

Reference: the Go pserver snapshotted its SHARD of the distributed state
with {uuid, md5, timestamp} meta and restored on restart
(/root/reference/go/pserver/service.go:120-203,346;
doc/design/cluster_train/checkpointing.md).  Here the executor holds the
whole mesh-sharded state as global jax Arrays, so the snapshot gathers
each state to one host array (placement-independent by construction) and
reuses io.py's meta/publish/GC protocol; restore re-places every array
under the CURRENT executor's shardings, so a run saved on a dp-8 mesh
restores onto dp-4 (or any mesh with the same logical axes sizes where
it matters — e.g. the pipeline stage count) with re-placement for free.
"""
from __future__ import annotations

import os
import uuid as uuid_mod

import numpy as np

import jax

STATES_FILENAME = "sharded_states.npz"
PSERVER_SHARD_FILENAME = "pserver_shard.npz"


def latest_pserver_shard(snapshot_dir):
    """Newest md5-valid pserver shard snapshot under `snapshot_dir`:
    ``({name: host array}, round, meta)`` or ``(None, 0, None)``.

    Shared by VariableServer.restore_snapshot (a replacement pserver
    resuming its slot) and the elastic ClusterController (sourcing a
    DEAD member's shards during a rebalance,
    go/pserver/service.go:120-203 semantics)."""
    from .. import io as _io

    cp_dir, meta = _io.latest_checkpoint(
        snapshot_dir,
        require=lambda d: os.path.exists(
            os.path.join(d, PSERVER_SHARD_FILENAME)))
    if cp_dir is None:
        return None, 0, None
    with np.load(os.path.join(cp_dir, PSERVER_SHARD_FILENAME)) as z:
        data = {n: z[n] for n in z.files}
    rnd = int(meta.get("trainer_args", {}).get("round", 0))
    return data, rnd, meta


class ShardedCheckpointMixin:
    """Adds save_checkpoint/restore_checkpoint to an executor exposing
    `_states` (name -> global Array), `_state_shardings`, `_step`,
    and `mesh`."""

    def save_checkpoint(self, dirname, trainer_args=None,
                        max_keep: int = 3) -> str:
        """Snapshot the sharded training state (params + optimizer
        accumulators, incl. ZeRO-1 shards) under `dirname` with
        {uuid, md5, timestamp} meta.  Returns the uuid.

        Single-process: gathers each global array to host and writes one
        npz.  Multi-process SPMD: EACH process writes only its
        addressable shards (data + global index slices) to its own
        `sharded_states.pK_of_N.npz` — the reference pserver's
        per-shard snapshot discipline
        (/root/reference/go/pserver/service.go:120-203) — then process 0
        alone computes the md5 over the assembled directory and
        publishes the meta/__latest__ pointer, with sync_global_devices
        barriers standing in for etcd's coordination.  Requires a
        filesystem shared by all processes (the normal checkpoint
        setup), because restore may re-shard across a different process
        count."""
        from .. import io as _io
        from ..core.resilience import fault_injector

        # chaos hook: lets tests model a process dying mid-snapshot (the
        # torn write the md5-on-restore check exists to catch)
        fault_injector().fire("checkpoint.save")

        nproc = jax.process_count()
        if nproc == 1:
            cp_uuid = uuid_mod.uuid4().hex
            cp_dir = os.path.join(dirname,
                                  f"{_io.CHECKPOINT_PREFIX}_{cp_uuid}")
            os.makedirs(cp_dir, exist_ok=True)
            host = {n: np.asarray(v) for n, v in self._states.items()}
            np.savez(os.path.join(cp_dir, STATES_FILENAME), **host)
            args = dict(trainer_args or {})
            args.setdefault("step", self._step)
            args.setdefault("mesh_axes", dict(self.mesh.shape))
            _io.publish_checkpoint(dirname, cp_uuid, cp_dir, args,
                                   max_keep)
            return cp_uuid

        from jax.experimental import multihost_utils

        pid = jax.process_index()
        # all processes must agree on the uuid: broadcast process 0's
        raw = np.frombuffer(uuid_mod.uuid4().bytes, np.uint8)
        raw = np.asarray(
            multihost_utils.broadcast_one_to_all(raw), np.uint8)
        cp_uuid = raw.tobytes().hex()
        cp_dir = os.path.join(dirname,
                              f"{_io.CHECKPOINT_PREFIX}_{cp_uuid}")
        os.makedirs(cp_dir, exist_ok=True)
        payload = {}
        for n, arr in self._states.items():
            for i, sh in enumerate(arr.addressable_shards):
                if sh.replica_id != 0:
                    continue  # one copy of replicated shards per process
                idx = tuple(
                    (0 if s.start is None else int(s.start),
                     arr.shape[d] if s.stop is None else int(s.stop))
                    for d, s in enumerate(sh.index))
                payload[f"{n}//{i}//data"] = np.asarray(sh.data)
                payload[f"{n}//{i}//index"] = np.asarray(idx, np.int64)
            payload[f"{n}//shape"] = np.asarray(arr.shape, np.int64)
            payload[f"{n}//dtype"] = np.asarray(
                str(np.dtype(arr.dtype)))
        np.savez(os.path.join(cp_dir,
                              f"sharded_states.p{pid}_of_{nproc}.npz"),
                 **payload)
        # every shard file must exist before process 0 hashes the dir
        multihost_utils.sync_global_devices(f"ckpt_save_{cp_uuid}")
        if pid == 0:
            args = dict(trainer_args or {})
            args.setdefault("step", self._step)
            args.setdefault("mesh_axes", dict(self.mesh.shape))
            args.setdefault("n_processes", nproc)
            _io.publish_checkpoint(dirname, cp_uuid, cp_dir, args,
                                   max_keep)
        multihost_utils.sync_global_devices(f"ckpt_pub_{cp_uuid}")
        return cp_uuid

    @staticmethod
    def _has_sharded_states(d) -> bool:
        if os.path.exists(os.path.join(d, STATES_FILENAME)):
            return True
        return any(n.startswith("sharded_states.p") and n.endswith(".npz")
                   for n in os.listdir(d))

    @staticmethod
    def _load_shard_files(cp_dir):
        """Assemble {name: full host array} from the per-process shard
        files written by a multi-process save (any process count)."""
        import glob

        files = sorted(glob.glob(
            os.path.join(cp_dir, "sharded_states.p*_of_*.npz")))
        n_expect = int(files[0].rsplit("_of_", 1)[1].split(".")[0])
        if len(files) != n_expect:
            raise RuntimeError(
                f"checkpoint {cp_dir} holds {len(files)} shard files "
                f"but was written by {n_expect} processes — incomplete "
                "snapshot (md5 should have caught this)")
        shapes, dtypes, pieces = {}, {}, {}
        for f in files:
            with np.load(f) as z:
                for key in z.files:
                    head, kind = key.rsplit("//", 1)
                    if kind == "shape":
                        shapes[head] = tuple(int(x) for x in z[key])
                    elif kind == "dtype":
                        dtypes[head] = str(z[key])
                    elif kind == "data":
                        name = head.rsplit("//", 1)[0]
                        pieces.setdefault(name, []).append(
                            (z[head + "//index"], z[key]))
        out = {}
        for n, shape in shapes.items():
            full = np.empty(shape, np.dtype(dtypes[n]))
            seen = np.zeros(shape, bool) if shape else None
            for idx, data in pieces.get(n, []):
                sl = tuple(slice(int(a), int(b)) for a, b in idx)
                full[sl] = data
                if seen is not None:
                    seen[sl] = True
            if shape and not seen.all():
                raise RuntimeError(
                    f"checkpoint var {n!r}: shard files do not cover "
                    "the full array (corrupt or partial save)")
            if not shape:  # 0-d: single replica-0 shard
                for idx, data in pieces.get(n, []):
                    full[()] = data
            out[n] = full
        return out

    def restore_checkpoint(self, dirname):
        """Restore the latest valid (md5-verified) snapshot under
        `dirname` onto THIS executor's mesh — the saved arrays are
        global (single-process npz) or re-assembled from per-process
        shard files (multi-process save), so a different dp size OR
        process count just re-places them.  Restores the RNG step
        counter too.  Returns the snapshot meta, or None when no usable
        snapshot exists."""
        from .. import io as _io

        # the dir layout is shared with the serial io.save_checkpoint
        # protocol, so the latest valid snapshot may be a serial one
        # (persistables files, no sharded npz).  Mixed directories
        # happen (e.g. a serial warm-start save followed by sharded
        # training snapshots): restore the newest md5-valid snapshot
        # that DOES carry sharded state — warning loudly if that
        # skips a newer serial snapshot, since resuming from it rewinds
        # past whatever progress the serial save recorded.
        cp_dir, meta = _io.latest_checkpoint(
            dirname, require=self._has_sharded_states)
        if cp_dir is None:
            if (not os.path.isdir(dirname)
                    or not _io._checkpoints_by_time(dirname)):
                return None  # empty/absent directory: documented contract
            raise RuntimeError(
                f"no snapshot under {dirname} carries {STATES_FILENAME} — "
                "it holds serial Executor saves only; restore those with "
                "io.load_checkpoint, or point ParallelExecutor at a "
                "directory of sharded snapshots")
        # cheap newer-serial detection: metadata timestamps only, no md5
        newer = [m for _, name, m in _io._checkpoints_by_time(dirname)
                 if m.get("timestamp", 0) > meta.get("timestamp", 0)
                 and not os.path.exists(os.path.join(
                     dirname, name, STATES_FILENAME))]
        if newer:
            import warnings

            warnings.warn(
                f"restore_checkpoint: newer snapshot {newer[-1]['uuid']} "
                f"has no {STATES_FILENAME} (serial save); resuming from "
                f"older sharded snapshot {meta['uuid']} — training state "
                "rewinds to it", RuntimeWarning, stacklevel=2)
        path = os.path.join(cp_dir, STATES_FILENAME)
        if os.path.exists(path):
            with np.load(path) as z:
                data = {n: z[n] for n in z.files}
        else:
            data = self._load_shard_files(cp_dir)
        missing = sorted(set(self._states) - set(data))
        if missing:
            raise RuntimeError(
                f"checkpoint {meta['uuid']} lacks state var(s) "
                f"{missing} — was it saved from a different "
                "program/strategy?")
        bad_shape = [
            (n, data[n].shape, tuple(self._states[n].shape))
            for n in self._states
            if tuple(data[n].shape) != tuple(self._states[n].shape)]
        if bad_shape:
            raise RuntimeError(
                f"checkpoint {meta['uuid']} shape mismatch (saved vs "
                f"current): {bad_shape} — same names, different "
                "architecture?")
        self._states = {
            n: jax.device_put(data[n], self._state_shardings[n])
            for n in self._states
        }
        self._step = int(meta.get("trainer_args", {})
                         .get("step", self._step))
        return meta
