"""Device-mesh utilities — the TPU answer to device enumeration and
process-group setup.

Replaces (SURVEY.md §2.5/§5.8): `get_places_op`
(/root/reference/paddle/fluid/operators/get_places_op.cc), NCCL communicator
init (operators/nccl_op.cc ncclInit), pserver endpoint lists
(distribute_transpiler.py pserver_endpoints) and etcd membership
(go/pserver/etcd_client.go).  On TPU, membership is the jax distributed
coordination service and topology is a `jax.sharding.Mesh` whose axes map
onto ICI; DCN-spanning meshes put the slowest-varying axis across hosts.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "get_places", "data_sharding", "replicated",
           "init_distributed", "PartitionSpec", "NamedSharding"]


def get_places(device_count: Optional[int] = None):
    """Device list (reference get_places_op / fluid.layers.get_places)."""
    devs = jax.devices()
    return devs[:device_count] if device_count else devs


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None
              ) -> Mesh:
    """Build a named mesh, e.g. make_mesh({'dp': 2, 'tp': 4}).

    Axis order follows dict order: earlier axes vary slowest — put the
    inter-host (DCN) axis first, ICI axes last, so collectives on the
    fast-varying axes ride ICI neighbors."""
    names = tuple(axes.keys())
    shape = tuple(axes.values())
    n = int(np.prod(shape))
    devs = list(devices if devices is not None else jax.devices())[:n]
    if len(devs) < n:
        raise ValueError(f"mesh needs {n} devices, have {len(devs)}")
    return Mesh(np.asarray(devs).reshape(shape), names)


def data_sharding(mesh: Mesh, batch_axis: str = "dp") -> NamedSharding:
    """Shard dim-0 (batch) over `batch_axis`, replicate the rest."""
    return NamedSharding(mesh, PartitionSpec(batch_axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: int = 1, process_id: int = 0):
    """Multi-host bring-up (replaces etcd registration + gRPC endpoints):
    wires this process into the jax coordination service.  No-op for
    single-process runs.

    Arguments default from the PADDLE_TPU_{COORDINATOR,NUM_PROCESSES,
    PROCESS_ID} env vars set by tools/launch.py --coordinator mode."""
    import os
    if coordinator_address is None:
        coordinator_address = os.environ.get("PADDLE_TPU_COORDINATOR")
        if coordinator_address is not None:
            num_processes = int(
                os.environ.get("PADDLE_TPU_NUM_PROCESSES", "1"))
            process_id = int(os.environ.get("PADDLE_TPU_PROCESS_ID", "0"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def count_collectives(hlo_text: str) -> dict:
    """Counts of cross-device collective instructions in optimized HLO
    text — the one shared digest behind ParallelExecutor.
    compiled_collectives and composite.collective_counts.  Instruction
    forms: `<name> = <type> <op>(`; async pairs appear as
    <op>-start(/<op>-done( and count once.  `<op>(` never matches operand
    references (those are `%<op>.N`)."""
    import re

    out = {}
    for op in ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all"):
        n = len(re.findall(rf"{op}(?:-start)?\(", hlo_text))
        if n:
            out[op] = n
    return out
