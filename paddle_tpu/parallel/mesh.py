"""Device-mesh utilities — the TPU answer to device enumeration and
process-group setup.

Replaces (SURVEY.md §2.5/§5.8): `get_places_op`
(/root/reference/paddle/fluid/operators/get_places_op.cc), NCCL communicator
init (operators/nccl_op.cc ncclInit), pserver endpoint lists
(distribute_transpiler.py pserver_endpoints) and etcd membership
(go/pserver/etcd_client.go).  On TPU, membership is the jax distributed
coordination service and topology is a `jax.sharding.Mesh` whose axes map
onto ICI; DCN-spanning meshes put the slowest-varying axis across hosts.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "get_places", "data_sharding", "replicated",
           "init_distributed", "PartitionSpec", "NamedSharding",
           "shard_map", "pvary"]


# ---------------------------------------------------------------------------
# shard_map entry-point shim
# ---------------------------------------------------------------------------
# jax moved shard_map from jax.experimental.shard_map (kwargs `auto`,
# `check_rep`) to jax.shard_map (kwargs `axis_names`, `check_vma`).
# Every shard_map in this package goes through THIS helper so the
# version split lives in exactly one place; callers use the modern
# surface (`axis_names` = the manual axes) and the shim translates for
# whichever entry point the installed jax provides.

_NEW_SHARD_MAP = getattr(jax, "shard_map", None)
if _NEW_SHARD_MAP is None:
    from jax.experimental.shard_map import shard_map as _EXP_SHARD_MAP
else:
    _EXP_SHARD_MAP = None


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None):
    """Map `f` over `mesh` shards (the jax.shard_map contract).

    `axis_names`: the MANUAL axes (values inside `f` have a local view
    of them; collectives may reference them).  Omitted/None = all mesh
    axes manual.  Axes left out stay in GSPMD-auto mode: arrays keep
    their NamedShardings over them and XLA propagates/inserts their
    collectives.

    Replication checking is disabled on both entry points: the bodies
    in this package mix manual and auto axes plus masked psums, and the
    old-jax checker rejects exactly the invariant-to-varying casts the
    new jax expresses with `pvary` (shimmed to a no-op below when the
    primitive is absent — semantically right because an unchecked body
    already treats every value as varying).

    Old-jax degradation: jax.experimental.shard_map's partial-auto mode
    (`auto=`) is unusable with this jaxlib's SPMD partitioner
    (axis_index lowers to a PartitionId the partitioner rejects, and
    ppermute trips a hard CHECK in spmd_partitioner.cc), so auto axes
    fall back to manual-and-GATHERED there: in_specs never mention
    them, so shard_map gathers inputs along those axes and the body
    computes replicated over them.  Numerics are identical; the auto
    axes simply stop sharding compute until a jax with a working
    partial-auto mode is installed."""
    if _NEW_SHARD_MAP is not None:
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = frozenset(axis_names)
        try:
            return _NEW_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_vma=False,
                                  **kw)
        except TypeError:
            # jax with jax.shard_map but the older (check_rep=, auto=)
            # spelling: translate axis_names to its auto= complement
            kw = {}
            if axis_names is not None:
                kw["auto"] = (frozenset(mesh.axis_names)
                              - frozenset(axis_names))
            try:
                return _NEW_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                                      out_specs=out_specs,
                                      check_rep=False, **kw)
            except TypeError:
                # no partial-auto support at all: degrade to
                # manual-and-gathered like the experimental path
                return _NEW_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                                      out_specs=out_specs,
                                      check_rep=False)
    return _EXP_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


def pvary(x, axis_names):
    """Cast a replicated value to device-varying over `axis_names`
    (jax.lax.pvary / the older pcast(to="varying")).  On jax versions
    without the primitive this is the identity: those versions'
    shard_map runs with replication checking off, where every value is
    already treated as varying and the cast has no semantic content."""
    fn = getattr(jax.lax, "pvary", None)
    if fn is None:
        fn = getattr(jax.lax, "pcast", None)
        if fn is not None:
            return fn(x, tuple(axis_names), to="varying")
        return x
    return fn(x, tuple(axis_names))


def get_places(device_count: Optional[int] = None):
    """Device list (reference get_places_op / fluid.layers.get_places)."""
    devs = jax.devices()
    return devs[:device_count] if device_count else devs


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None
              ) -> Mesh:
    """Build a named mesh, e.g. make_mesh({'dp': 2, 'tp': 4}).

    Axis order follows dict order: earlier axes vary slowest — put the
    inter-host (DCN) axis first, ICI axes last, so collectives on the
    fast-varying axes ride ICI neighbors."""
    names = tuple(axes.keys())
    shape = tuple(axes.values())
    n = int(np.prod(shape))
    devs = list(devices if devices is not None else jax.devices())[:n]
    if len(devs) < n:
        raise ValueError(f"mesh needs {n} devices, have {len(devs)}")
    return Mesh(np.asarray(devs).reshape(shape), names)


def data_sharding(mesh: Mesh, batch_axis: str = "dp") -> NamedSharding:
    """Shard dim-0 (batch) over `batch_axis`, replicate the rest."""
    return NamedSharding(mesh, PartitionSpec(batch_axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: int = 1, process_id: int = 0):
    """Multi-host bring-up (replaces etcd registration + gRPC endpoints):
    wires this process into the jax coordination service.  No-op for
    single-process runs.

    Arguments default from the PADDLE_TPU_{COORDINATOR,NUM_PROCESSES,
    PROCESS_ID} env vars set by tools/launch.py --coordinator mode."""
    import os
    if coordinator_address is None:
        coordinator_address = os.environ.get("PADDLE_TPU_COORDINATOR")
        if coordinator_address is not None:
            num_processes = int(
                os.environ.get("PADDLE_TPU_NUM_PROCESSES", "1"))
            process_id = int(os.environ.get("PADDLE_TPU_PROCESS_ID", "0"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def count_collectives(hlo_text: str) -> dict:
    """Counts of cross-device collective instructions in optimized HLO
    text — the one shared digest behind ParallelExecutor.
    compiled_collectives and composite.collective_counts.  Instruction
    forms: `<name> = <type> <op>(`; async pairs appear as
    <op>-start(/<op>-done( and count once.  `<op>(` never matches operand
    references (those are `%<op>.N`)."""
    import re

    out = {}
    for op in ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all"):
        n = len(re.findall(rf"{op}(?:-start)?\(", hlo_text))
        if n:
            out[op] = n
    return out


# dtype token -> bytes/element for HLO result shapes (collective_bytes)
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_HLO_SHAPE_RE = r"(?:pred|[suf]\d+|bf16|c\d+)\[[\d,]*\]"


def collective_bytes(hlo_text: str) -> dict:
    """Payload BYTES of cross-device collective instructions in
    optimized HLO text: per collective type, the summed element bytes of
    every instruction's result shape(s) — tuple-shaped and async
    (`-start`) forms included.  This is the measured side of the static
    `analysis.cost_model.estimate_comm` volume (same logical-payload
    convention: an all-reduce's result shape IS its operand shape)."""
    import re

    def shape_bytes(tok: str) -> int:
        dtype, dims = tok.split("[", 1)
        dims = dims.rstrip("]")
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        return n * _HLO_DTYPE_BYTES.get(dtype, 4)

    out = {}
    for op in ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all"):
        total = 0
        # `%name = <shape> op(` and `%name = (<shape>, <shape>) op(`
        for m in re.finditer(
                rf"=\s*(\(?(?:{_HLO_SHAPE_RE}(?:\{{[\d,]*\}})?"
                rf"(?:,\s*)?)+\)?)\s*{op}((?:-start)?)\(", hlo_text):
            toks = re.findall(_HLO_SHAPE_RE, m.group(1))
            if m.group(2) and len(toks) > 1:
                # async `-start` result is a tuple of (operand, result
                # [, context scalars]) — the logical payload is the
                # RESULT shape only (for all-reduce/permute operand and
                # result are identical; summing both would double-count
                # vs the sync form).  Drop scalar context tokens (the
                # u32[] pair some backends append to permute-start)
                # BEFORE picking the result, or the payload reads as
                # 4 bytes
                tensors = [t for t in toks if "[]" not in t]
                toks = (tensors or toks)[-1:]
            for tok in toks:
                total += shape_bytes(tok)
        if total:
            out[op] = total
    return out
