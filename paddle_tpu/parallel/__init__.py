"""Parallelism package: mesh, SPMD ParallelExecutor, collectives,
ring/Ulysses attention, sharded embedding, GPipe pipeline (SURVEY.md
§2.5/§5.8 rebuilt as ICI-native XLA collectives)."""
from . import collective  # noqa: F401  (registers c_* ops)
from .collective import (  # noqa: F401
    shard_embedding_table,
    sharded_embedding_grad,
    sharded_embedding_lookup,
)
from .executor import (  # noqa: F401
    DistributeTranspiler,
    ParallelExecutor,
    ShardingTranspiler,
    SimpleDistributeTranspiler,
)
from .spmd import SpmdPlan, propagate_sharding  # noqa: F401
from .mesh import (  # noqa: F401
    NamedSharding,
    PartitionSpec,
    data_sharding,
    get_places,
    init_distributed,
    make_mesh,
    replicated,
)
from .composite import (  # noqa: F401
    collective_counts,
    make_composite_step,
    make_transformer_composite_step,
)
from .moe import (  # noqa: F401
    drop_rate,
    load_balance,
    moe_dense,
    moe_ffn,
    moe_ffn_a2a,
    moe_gate,
)
from .pipeline import (  # noqa: F401
    microbatch,
    spmd_pipeline,
    stack_stage_params,
    unmicrobatch,
)
from .pipeline_program import PipelineExecutor  # noqa: F401
from .ring_attention import (  # noqa: F401
    all_to_all_attention,
    attention_reference,
    ring_attention,
)
