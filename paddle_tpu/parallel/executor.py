"""ParallelExecutor: SPMD execution of a Program over a device mesh.

This one component replaces ALL of the reference's parallelism machinery
(SURVEY.md §2.5):
  * `parallel_do_op` worker threads + per-place scopes + grad sum
    (/root/reference/paddle/fluid/operators/parallel_do_op.cc:113-346)
    -> batch dp-sharded into one jit; XLA splits the work per device.
  * NCCL allreduce ops (operators/nccl_op.cu.cc, doc/design/paddle_nccl.md)
    -> the gradient all-reduce is inserted BY XLA's sharding propagation
    (replicated params x dp-sharded batch), riding ICI.
  * DistributeTranspiler + gRPC pserver (distribute_transpiler.py:133,
    operators/listen_and_serv_op.cc) -> `shard_optimizer_states=True`
    partitions optimizer accumulators across the mesh (the pserver
    block-shard analogue, ZeRO-1 numerics == sync pserver SGD), with
    reduce-scatter/all-gather chosen by the compiler.
  * MultiGradientMachine ring (gserver/gradientmachines/MultiGradientMachine.h)
    -> same allreduce, no hand-rolled ring.

Tensor-parallel layers: pass `param_shardings={param_name: PartitionSpec}`
to split weight matrices over a 'tp'/'mp' axis; activations follow by
propagation.
"""
from __future__ import annotations

import itertools
import time
from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.executor import CPUPlace, Executor, program_to_fn
from ..core.framework import Variable, default_startup_program
from ..core.scope import Scope
from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing
from .checkpoint import ShardedCheckpointMixin
from .mesh import make_mesh

# same series as core.executor's run histogram (get-or-create by name),
# with a "pe<N>" instance label and mode="parallel"
_PE_IDS = itertools.count()
_M_RUN_SECONDS = obs_metrics.histogram(
    "paddle_tpu_executor_run_seconds",
    "Executor.run wall latency by execution mode", ("exe", "mode"))


def _amp_enabled() -> bool:
    from ..amp import is_bf16_enabled
    return is_bf16_enabled()


def _trace_flags() -> tuple:
    """Snapshot of every flag read at TRACE time by op lowerings (plus
    memory_optimize, which decides feed donation, and
    overlap_bucket_bytes, which shapes the overlap step's grad buckets
    — both part of the built executable); a jit built under one
    snapshot must not serve another."""
    from ..core.flags import get_flag
    return (_amp_enabled(), get_flag("flash_min_seq_k"),
            get_flag("flash_pack_heads"), get_flag("flash_block_q"),
            get_flag("flash_block_k"), get_flag("conv_layout"),
            get_flag("memory_optimize"),
            get_flag("overlap_bucket_bytes"),
            get_flag("serving_kernels"))

__all__ = ["ParallelExecutor", "DistributeTranspiler",
           "SimpleDistributeTranspiler", "ShardingTranspiler"]


class ParallelExecutor(ShardedCheckpointMixin):
    def __init__(
        self,
        program,
        feed_names: Sequence[str],
        fetch_list: Sequence,
        mesh,
        startup_program=None,
        batch_axis: str = "dp",
        param_shardings: Optional[Dict[str, P]] = None,
        shard_optimizer_states: bool = False,
        seed: int = 0,
        overlap: str = "off",
        spmd_plan=None,
    ):
        if isinstance(mesh, dict):
            mesh = make_mesh(mesh)
        if overlap not in ("off", "auto", "bucketed"):
            raise ValueError(
                f"overlap must be 'off', 'auto' or 'bucketed', got "
                f"{overlap!r}")
        self.mesh: Mesh = mesh
        self.batch_axis = batch_axis
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = [
            v.name if isinstance(v, Variable) else str(v)
            for v in fetch_list
        ]
        # PADDLE_TPU_VERIFY pre-flight, same contract as Executor.run
        # (gated inside preflight): a bad graph fails here in ms, not
        # minutes into the SPMD trace
        from ..analysis import preflight

        preflight(program, feed_names=self.feed_names,
                  fetch_names=self.fetch_names)
        # sharding annotations on the Program IR (layers.shard /
        # data(sharding=...)): complete them via the spmd propagation
        # and fold the derived placements under any explicit
        # param_shardings (explicit names win).  Unannotated programs
        # skip this entirely — plan stays None and the legacy defaults
        # (replicated params, batch-over-dp feeds) apply.
        from .spmd import (has_annotations, propagate_sharding,
                           spec_to_partition)

        blk0 = program.global_block()
        if spmd_plan is None and has_annotations(blk0):
            spmd_plan = propagate_sharding(
                program, mesh_axes={a: int(mesh.shape[a])
                                    for a in mesh.axis_names},
                batch_axis=batch_axis)
        self._spmd_plan = spmd_plan
        if spmd_plan is not None:
            spmd_plan.check()
            derived = {n: spec_to_partition(s)
                       for n, s in spmd_plan.param_specs.items()}
            derived.update(param_shardings or {})
            param_shardings = derived
        self._feed_specs = dict(spmd_plan.feed_specs) if spmd_plan \
            else {}
        self._fn = program_to_fn(program, self.feed_names, self.fetch_names)
        # explicit `donate=True` var hints fail HERE (build time) when
        # unsafe — e.g. a donated feed that is also fetched — not as a
        # deleted-buffer crash mid-train
        blk = program.global_block()
        hinted = [n for n in self.feed_names
                  if getattr(blk.vars.get(n), "donate", False)]
        if hinted:
            from ..memory_optimization_transpiler import plan_donation

            rw = [n for n in self._fn.state_in_names
                  if n in self._fn.state_out_names]
            plan_donation(program, self.feed_names, self.fetch_names,
                          state_rw_names=rw, requested=hinted).check()
        self._seed = seed
        self._step = 0
        param_shardings = dict(param_shardings or {})
        # kept for the overlap eligibility check: explicitly passed
        # placements must stand the overlap down exactly like derived
        # ones (the manual-dp shard_map would gather them)
        self._param_shardings = dict(param_shardings)

        # --- initialize states on host, then place with shardings ---------
        startup = startup_program or default_startup_program()
        scope = Scope()
        Executor(CPUPlace()).run(startup, scope=scope)

        param_names = {
            v.name for v in program.global_block().all_parameters()
        }
        self._state_shardings = {}
        states = {}
        for n in self._fn.state_in_names:
            val = scope.find_var(n)
            if val is None:
                raise RuntimeError(
                    f"state var {n!r} not produced by the startup program")
            spec = self._spec_for(n, np.asarray(val), param_names,
                                  param_shardings,
                                  shard_optimizer_states)
            sh = NamedSharding(self.mesh, spec)
            states[n] = jax.device_put(np.asarray(val), sh)
            self._state_shardings[n] = sh
        self._states = states

        data_sh = NamedSharding(self.mesh, P(self.batch_axis))
        self._data_sharding = data_sh
        # per-feed shardings: annotated feeds keep their spec (e.g. a
        # replicated lookup table fed alongside dp-sharded batches);
        # everything else gets the batch-over-dp default
        self._feed_shardings = {
            n: NamedSharding(self.mesh,
                             spec_to_partition(self._feed_specs[n]))
            for n in self.feed_names if n in self._feed_specs
        }

        fn = self._fn

        def step(feeds, states, key):
            fetches, new_states = fn(feeds, states, key)
            return fetches, new_states

        self._step_fn = step
        # compute/collective overlap (docs/performance.md "Multichip
        # sharding"): lower the step as shard_map over the dp axis with
        # the gradient all-reduce issued as size-capped bucketed psums,
        # so XLA's scheduler overlaps early buckets with the remaining
        # backward.  'auto' falls back to the GSPMD step (reason kept in
        # overlap_info) when the program shape rules it out; explicit
        # 'bucketed' raises instead.
        self.overlap_info = {"mode": "off",
                             "reason": "overlap='off' requested"}
        self._overlap_cfg = None
        # serving-kernel tier (docs/performance.md "Serving kernels"):
        # one Selection per executor so fallback series are reclaimed
        # on close; consulted by _make_overlap_step for the fused
        # per-bucket optimizer update
        from ..kernels import registry as _kernel_registry

        self._kernel_selection = _kernel_registry.Selection()
        if overlap != "off":
            cfg, reason = self._analyze_overlap(program, blk)
            if cfg is None:
                if overlap == "bucketed":
                    raise ValueError(
                        f"overlap='bucketed' is not applicable to this "
                        f"program: {reason}")
                self.overlap_info = {"mode": "off", "reason": reason}
            else:
                self._overlap_cfg = cfg
                self.overlap_info = {"mode": "bucketed"}
        self._jit_step = self._make_jit_step()
        self._trace_flags_state = _trace_flags()

    def _make_jit_step(self):
        # donation plan (memory_optimization_transpiler via
        # program_to_fn): states are donated always — `run` rebinds
        # self._states to the returned dict, so the old buffers die with
        # the step (ZeRO-style in-place update).  Feed buffers (always
        # freshly device_put from host in `run`) join under the
        # memory_optimize flag when the plan covers every feed — jit
        # donation is per-argument, and a fetched feed must survive.
        from ..core.flags import get_flag

        donate = [1]
        plan = self._fn.donation_plan
        if get_flag("memory_optimize") and \
                set(self.feed_names) <= plan.feeds:
            donate.insert(0, 0)
        if self._overlap_cfg is not None:
            return self._make_overlap_step(tuple(donate))
        return jax.jit(
            self._step_fn,
            out_shardings=(None, self._out_state_shardings()),
            donate_argnums=tuple(donate),
        )

    # -- compute/collective overlap (bucketed grad all-reduce) --------------
    def _analyze_overlap(self, program, block):
        """Validate the program for the overlapped lowering and extract
        its structure.  Returns (cfg, None) or (None, reason).

        The overlapped step runs every op up to the first gradient
        consumer INSIDE a shard_map over the dp axis (each shard
        computes forward+backward on its local batch rows), reduces the
        parameter gradients with bucketed psums, and runs the update
        section (grad clip + optimizer ops) outside on the reduced
        values — numerically the serial program up to float
        associativity, because a mean loss over the global batch equals
        the pmean of per-shard local means."""
        from ..core import registry as op_registry
        from ..core.framework import (EMPTY_VAR_NAMES, Parameter,
                                      grad_var_name)

        ops = block.ops
        opt_ops = [op for op in ops
                   if "Param" in op.inputs and "ParamOut" in op.outputs]
        if not opt_ops:
            return None, ("no optimizer ops — the overlap lowers a "
                          "training step")
        # the reduction point is the first consumer of any RAW parameter
        # gradient — NOT the optimizer's Grad input, which may be a
        # clipped/regularized derivative of it: grad-clip (e.g.
        # global-norm) must see the REDUCED full-batch gradients, so
        # clip/regularizer ops belong to the update section
        all_produced = {n for op in ops for n in op.output_names()}
        grad_of = {}
        for v in block.vars.values():
            if isinstance(v, Parameter) and getattr(v, "trainable", True):
                g = grad_var_name(v.name)
                if g in all_produced:
                    grad_of[g] = v.name
        if not grad_of:
            return None, "no parameter gradients in the program"
        grad_names = set(grad_of)
        split = next((i for i, op in enumerate(ops)
                      if set(op.input_names()) & grad_names), None)
        if split is None:
            return None, "no op consumes the parameter gradients"
        produced = set()
        last_prod = {}
        for i, op in enumerate(ops[:split]):
            for n in op.output_names():
                produced.add(n)
                if n in grad_names:
                    last_prod[n] = i
        if not grad_names <= produced:
            missing = sorted(grad_names - produced)
            return None, (f"gradient(s) {missing} are produced after "
                          "their first consumer")
        if self._spmd_plan is not None and self._spmd_plan.model_axes:
            return None, (
                f"model-parallel placements over "
                f"{self._spmd_plan.model_axes} — the GSPMD step keeps "
                "them sharded; the manual-dp overlap would gather them")
        placed = sorted(n for n, s in self._param_shardings.items()
                        if s is not None and any(e is not None
                                                 for e in tuple(s)))
        if placed:
            return None, (
                f"explicit param_shardings on {placed} — the GSPMD "
                "step keeps them sharded; the manual-dp overlap would "
                "gather them")

        # the grad reduction is pmean (psum / dp), which equals the
        # serial gradient ONLY for a batch-MEAN loss (the book
        # convention; same assumption the 1F1B schedule documents) —
        # require the backward seed's loss var to come from a mean op
        from ..core.framework import GRAD_SUFFIX
        from .spmd import backward_start_index

        seed_idx = backward_start_index(block)
        if seed_idx >= split:
            return None, "no backward section (loss@GRAD seed) found"
        seed_out = ops[seed_idx].output_names()[0]
        loss_name = seed_out[:-len(GRAD_SUFFIX)]
        loss_var = block.vars.get(loss_name)
        if loss_var is None or loss_var.op is None or \
                loss_var.op.type != "mean":
            return None, (
                f"loss {loss_name!r} is not produced by a mean op — "
                "per-shard gradients averaged over dp only equal the "
                "serial gradient for a batch-mean loss")

        persistable = {v.name for v in program.list_vars()
                       if v.persistable}
        for i, op in enumerate(ops):
            if any(isinstance(v, dict) and "__block__" in v
                   for v in op.attrs.values()):
                return None, f"control-flow op {op.type!r} (sub-blocks)"
            try:
                info = op_registry.get_op_info(op.type)
            except KeyError:
                return None, f"unregistered op {op.type!r}"
            if info.host:
                return None, f"host op {op.type!r}"
            if info.random and not op.attrs.get("is_test", False):
                if i >= split:
                    # the update section runs under a different PRNG
                    # stream (fold_in(key, 1), indices restarting), so
                    # ANY stochastic op there diverges from serial
                    return None, (
                        f"stochastic op {op.type!r} in the update "
                        "section — its draws would differ from serial")
                if op.type != "dropout":
                    return None, (
                        f"stochastic op {op.type!r}: only dropout has "
                        "the batch-position-keyed PRNG that keeps "
                        "per-shard draws equal to serial")
            if i < split:
                if (op.type == "batch_norm"
                        and not op.attrs.get("is_test", False)):
                    return None, ("training-mode batch_norm couples "
                                  "rows across the dp shards")
                if any(n and n in persistable
                       for n in op.output_names()):
                    return None, (
                        f"op {op.type!r} writes persistable state "
                        "inside the sharded section")

        # the update section may read only persistables, the reduced
        # grads, and its own intermediates
        upd_prod = set()
        for op in ops[split:]:
            for n in op.input_names():
                if (not n or n in EMPTY_VAR_NAMES or n in grad_names
                        or n in upd_prod or n in persistable):
                    continue
                return None, (
                    f"update-section op {op.type} reads forward value "
                    f"{n!r} (e.g. a per-example regularizer input)")
            upd_prod.update(op.output_names())

        for n in self.feed_names:
            v = block.vars.get(n)
            if v is None:
                continue
            if v.lod_level:
                return None, f"LoD feed {n!r} (host-side metadata)"
            if not v.shape or v.shape[0] != -1:
                return None, f"feed {n!r} has no leading batch dim"
            spec = self._feed_specs.get(n)
            if spec is not None and (
                    not spec or spec[0] != self.batch_axis):
                return None, (
                    f"feed {n!r} is annotated {spec}, not sharded over "
                    f"the '{self.batch_axis}' batch axis")

        fetch_kinds = {}
        for n in self.fetch_names:
            if n not in produced:
                return None, (f"fetch {n!r} is produced by the update "
                              "section (not supported under overlap)")
            v = block.vars.get(n)
            if v is not None and v.shape and v.shape[0] == -1:
                fetch_kinds[n] = "batch"
                continue
            # non-batch fetches are combined by pmean over dp — only
            # correct for batch-mean quantities, so require a
            # mean-semantics producer
            if v is None or v.op is None or v.op.type not in (
                    "mean", "accuracy"):
                return None, (
                    f"fetch {n!r} is not a per-row output or a batch "
                    "mean — its per-shard values cannot be combined")
            fetch_kinds[n] = "mean"

        inside_state = sorted({
            n for op in ops[:split] for n in op.input_names()
            if n in persistable})
        grad_order = sorted(grad_names, key=lambda g: last_prod[g])
        grad_meta = []
        for g in grad_order:
            pv = block.vars.get(grad_of[g])
            if pv is None or pv.shape is None or any(
                    d < 0 for d in pv.shape):
                return None, f"parameter {grad_of[g]!r} has no static shape"
            grad_meta.append((g, tuple(pv.shape), pv.dtype or "float32"))
        return {
            "split": split,
            "inside": tuple(ops[:split]),
            "update": tuple(ops[split:]),
            "grad_meta": grad_meta,
            "inside_state": inside_state,
            "fetch_kinds": fetch_kinds,
        }, None

    def _make_overlap_step(self, donate):
        from ..core.execution import DictEnv, ExecContext, run_op
        from ..core.flags import get_flag
        from .mesh import shard_map
        import jax.numpy as jnp

        cfg = self._overlap_cfg
        mesh, dp_ax = self.mesh, self.batch_axis
        dp = int(mesh.shape[dp_ax])
        inside_ops, update_ops = cfg["inside"], cfg["update"]
        fetch_kinds = cfg["fetch_kinds"]
        inside_state = cfg["inside_state"]

        # size-capped buckets in gradient PRODUCTION (backward) order,
        # one stream per dtype (a bucket is one concatenated psum):
        # early buckets' all-reduces become schedulable against the
        # remaining backward compute — the DDP overlap, in-program
        from ..core.types import np_dtype

        cap = int(get_flag("overlap_bucket_bytes"))
        buckets, cur, cur_bytes, cur_dt = [], [], 0, None
        for g, shape, dtype in cfg["grad_meta"]:
            nbytes = int(np.prod(shape, dtype=np.int64)
                         * np_dtype(dtype).itemsize)
            if cur and (dtype != cur_dt
                        or (cap > 0 and cur_bytes + nbytes > cap)
                        or cap <= 0):
                buckets.append(tuple(cur))
                cur, cur_bytes = [], 0
            cur.append((g, shape, dtype))
            cur_dt, cur_bytes = dtype, cur_bytes + nbytes
        if cur:
            buckets.append(tuple(cur))
        fused_plan = self._plan_fused_update(buckets, update_ops)
        self.overlap_info.update(
            buckets=len(buckets), grads=len(cfg["grad_meta"]),
            split=cfg["split"],
            update=("fused" if fused_plan is not None else
                    self._kernel_selection.chosen.get(
                        "fused_bucket_update", "per_op")))

        feed_in_specs = {n: P(dp_ax) for n in self.feed_names}
        state_in_specs = {n: P() for n in inside_state}
        fetch_out_specs = {n: (P(dp_ax) if k == "batch" else P())
                           for n, k in fetch_kinds.items()}
        grad_out_specs = {g: P() for g, _, _ in cfg["grad_meta"]}

        def local_fwd_bwd(feeds, ro, key_data):
            key = jax.random.wrap_key_data(key_data)
            env = DictEnv({**ro, **feeds})
            ctx = ExecContext(key, compiled=True)
            # dropout masks are batch-position keyed: offset this
            # shard's rows so the composed draw equals serial's
            mb = next(iter(feeds.values())).shape[0] if feeds else 0
            ctx.row_offset = jax.lax.axis_index(dp_ax) * mb
            for op in inside_ops:
                run_op(ctx, op, env)
            grads = {}
            for bucket in buckets:
                flat = jnp.concatenate(
                    [jnp.ravel(env.get(g)) for g, _, _ in bucket]) \
                    if len(bucket) > 1 else jnp.ravel(
                        env.get(bucket[0][0]))
                red = jax.lax.psum(flat, dp_ax) / dp
                off = 0
                for g, shape, _ in bucket:
                    size = int(np.prod(shape, dtype=np.int64))
                    grads[g] = red[off:off + size].reshape(shape)
                    off += size
            fetches = {}
            for n, kind in fetch_kinds.items():
                v = env.get(n)
                fetches[n] = (v if kind == "batch"
                              else jax.lax.pmean(v, dp_ax))
            return fetches, grads

        sharded = shard_map(
            local_fwd_bwd, mesh=mesh,
            in_specs=(feed_in_specs, state_in_specs, P()),
            out_specs=(fetch_out_specs, grad_out_specs))

        fetch_names = list(self.fetch_names)

        def step(feeds, states, key):
            fet, grads = sharded(
                feeds, {n: states[n] for n in inside_state},
                jax.random.key_data(key))
            if fused_plan is not None:
                # fused per-bucket update: ONE Pallas launch applies a
                # whole bucket's p -= lr*g over the concatenated flat
                # views instead of the per-parameter sgd op chain
                new_states = dict(states)
                for entries, lr_name, kern in fused_plan:
                    flat_p = jnp.concatenate(
                        [jnp.ravel(states[p]) for p, _, _ in entries]) \
                        if len(entries) > 1 \
                        else jnp.ravel(states[entries[0][0]])
                    flat_g = jnp.concatenate(
                        [jnp.ravel(grads[g]) for _, g, _ in entries]) \
                        if len(entries) > 1 \
                        else jnp.ravel(grads[entries[0][1]])
                    new_flat = kern(flat_p, flat_g, states[lr_name])
                    off = 0
                    for p, _, shape in entries:
                        size = int(np.prod(shape, dtype=np.int64))
                        new_states[p] = \
                            new_flat[off:off + size].reshape(shape)
                        off += size
                return {n: fet[n] for n in fetch_names}, new_states
            env = DictEnv({**states, **grads})
            ctx = ExecContext(jax.random.fold_in(key, 1), compiled=True)
            for op in update_ops:
                run_op(ctx, op, env)
            new_states = {n: env.d.get(n, states[n]) for n in states}
            return {n: fet[n] for n in fetch_names}, new_states

        return jax.jit(
            step,
            out_shardings=(None, self._out_state_shardings()),
            donate_argnums=donate,
        )

    def _plan_fused_update(self, buckets, update_ops):
        """Map the overlap buckets onto the fused Pallas bucket update
        (docs/performance.md "Serving kernels"): one kernel per bucket
        replaces the per-parameter sgd op chain WHEN the chain's shape
        allows it — every update op a plain dense `sgd` writing its
        param in place, fed the raw reduced bucket grad, all params of
        a bucket sharing one learning-rate scalar.  Anything fancier
        (momentum/adam, clipping chains, per-param LR) keeps the op
        chain, counted through the fallback registry.

        Returns [(entries, lr_name, kern)] with entries
        [(param, grad, shape)] in bucket order, or None."""
        structure = None
        grad_to_op = {}
        for op in update_ops:
            if op.type != "sgd":
                structure = "op_mix"
                break
            ps, gs = op.input("Param"), op.input("Grad")
            ls, pouts = op.input("LearningRate"), op.output("ParamOut")
            if len(ps) != 1 or len(gs) != 1 or len(ls) != 1 \
                    or pouts != ps:
                structure = "op_shape"
                break
            grad_to_op[gs[0]] = (ps[0], ls[0])

        plan = []
        if structure is None:
            for bucket in buckets:
                entries, lr_names = [], set()
                for g, shape, dtype in bucket:
                    if g not in grad_to_op:
                        # the op chain reads something other than the
                        # raw reduced grad (e.g. a clip rewrote it)
                        structure = "clipped_grads"
                        break
                    pname, lr_name = grad_to_op[g]
                    entries.append((pname, g, shape))
                    lr_names.add(lr_name)
                if structure is not None:
                    break
                if len(lr_names) != 1:
                    structure = "lr_mismatch"
                    break
                lr_name = lr_names.pop()
                if lr_name not in self._states:
                    structure = "lr_missing"
                    break
                numel = int(sum(np.prod(s, dtype=np.int64)
                                for _, _, s in entries))
                kern = self._kernel_selection.pick(
                    "fused_bucket_update", numel=numel,
                    dtype=str(bucket[0][2]))
                if kern is None:
                    return None
                plan.append((tuple(entries), lr_name, kern))
            if structure is None:
                return plan

        # chain shape ruled the fusion out: route the verdict through
        # the registry so it is counted (when armed) like any other
        # unsupported combination
        self._kernel_selection.pick("fused_bucket_update", numel=0,
                                    structure=structure)
        return None

    def _refresh_trace_flags(self):
        # trace-time flags (amp_bf16, flash_min_seq_k) are read inside op
        # lowerings; identical input avals would silently reuse an
        # executable traced under the old flag state, so any flip gets a
        # fresh jit cache (serial Executor: same flags in its cache keys)
        if _trace_flags() != self._trace_flags_state:
            self._jit_step = self._make_jit_step()
            self._trace_flags_state = _trace_flags()

    # -- sharding policy -----------------------------------------------------
    def _spec_for(self, name, val, param_names, param_shardings,
                  shard_opt) -> P:
        # explicit spec wins (params and their accumulators)
        for pname, spec in param_shardings.items():
            if name == pname:
                return spec
            if name.startswith(pname + "_") and name.endswith("_acc"):
                # accumulator inherits its parameter's sharding
                if tuple(val.shape) and len(spec) <= len(val.shape):
                    return spec
        if shard_opt and name.endswith("_acc") and val.ndim >= 1:
            # ZeRO-1 / pserver-shard analogue: split accumulator dim 0
            dp = self.mesh.shape[self.batch_axis]
            if val.shape[0] % dp == 0 and val.shape[0] >= dp:
                return P(self.batch_axis)
        return P()

    def _out_state_shardings(self):
        return {n: self._state_shardings[n]
                for n in sorted(set(self._fn.state_in_names)
                                | set(self._fn.state_out_names))
                if n in self._state_shardings} or None

    # -- execution -----------------------------------------------------------
    def run(self, feed: Dict, fetch_list=None, return_numpy=True):
        t0 = time.perf_counter()
        self._refresh_trace_flags()
        fetch_names = ([v.name if isinstance(v, Variable) else str(v)
                        for v in fetch_list]
                       if fetch_list is not None else self.fetch_names)
        assert fetch_names == self.fetch_names, \
            "fetch_list must match construction-time fetch_list"
        with obs_tracing.span("executor.run", mode="parallel"):
            feeds = {
                n: jax.device_put(
                    np.asarray(v),
                    self._feed_shardings.get(n, self._data_sharding))
                for n, v in feed.items()
            }
            key = jax.random.fold_in(jax.random.key(self._seed),
                                     self._step)
            self._step += 1
            fetches, self._states = self._jit_step(feeds, self._states,
                                                   key)
            out = [fetches[n] for n in fetch_names]
            if return_numpy:
                out = [np.asarray(v) for v in out]
        if obs_metrics.enabled():
            if not hasattr(self, "_m_run"):
                self._m_run_id = f"pe{next(_PE_IDS)}"
                self._m_run = _M_RUN_SECONDS.labels(
                    exe=self._m_run_id, mode="parallel")
            self._m_run.observe(time.perf_counter() - t0)
        return out

    def close(self):
        """Reclaim this instance's registry series (per-instance
        telemetry contract: churned executors must not grow every
        metrics dump without bound).  The executor stays usable."""
        if hasattr(self, "_m_run"):
            _M_RUN_SECONDS.remove(exe=self._m_run_id, mode="parallel")
        if hasattr(self, "_kernel_selection"):
            self._kernel_selection.close()

    def compiled_collectives(self, feed: Dict) -> Dict[str, int]:
        """Counts of cross-device collective ops in the optimized HLO of
        the train step compiled for `feed`'s shapes — pins the
        communication STRUCTURE of a mesh without the hardware (e.g.
        dp-N must show grad all-reduces and nothing else; run_scaling.py
        --virtual reports this per N alongside the no-op virtual
        throughput)."""
        from .mesh import count_collectives

        feeds = {
            n: jax.ShapeDtypeStruct(
                np.asarray(v).shape, np.asarray(v).dtype,
                sharding=self._feed_shardings.get(n,
                                                  self._data_sharding))
            for n, v in feed.items()
        }
        key = jax.random.key(self._seed)
        txt = self._jit_step.lower(feeds, self._states, key) \
            .compile().as_text()
        return count_collectives(txt)

    def state(self, name, return_numpy=True):
        v = self._states[name]
        return np.asarray(v) if return_numpy else v

    def set_state(self, name, value):
        self._states[name] = jax.device_put(
            np.asarray(value), self._state_shardings[name])


class DistributeTranspiler:
    """API-compatible entry point for the reference's transpiler workflow
    (/root/reference/python/paddle/v2/fluid/distribute_transpiler.py:133).

    The reference rewrites the program into trainer (split/send/concat) and
    per-pserver (listen_and_serv + optimize-block) programs.  On a TPU mesh
    none of that rewriting exists as program surgery: `transpile` records
    the mesh layout, `get_trainer_program` returns the ORIGINAL program
    (configuration-as-compilation — sharding is an execution property), and
    `build_executor` yields a ParallelExecutor where
      * grad aggregation = psum over the dp axis (was: send + fan-in barrier
        + sum at the pserver, listen_and_serv_op.cc:114-153)
      * optimizer-state sharding = ZeRO-1 accumulator partitioning (was:
        ~1024-element param blocks round-robined over pservers,
        distribute_transpiler.py:91-132)
    """

    def __init__(self):
        self._mesh_axes = None
        self._program = None
        self._startup = None
        self._shard_opt = True
        self._endpoints = []
        self._assign = {}          # param name -> endpoint
        self._pairs_by_ep = {}     # endpoint -> [(param, grad)]
        self._optimize_ops = []
        self._mode = None
        self._plan = None
        self._overlap = "auto"
        self._batch_axis = "dp"

    def transpile(self, optimize_ops=None, params_grads=None,
                  trainers=1, pservers: str = "", program=None,
                  startup_program=None,
                  mesh_axes: Optional[Dict[str, int]] = None,
                  mesh=None,
                  mode: Optional[str] = None,
                  shard_optimizer_states: bool = True,
                  split_method=None, sync_mode: bool = True,
                  overlap: str = "auto", batch_axis: str = "dp"):
        """Prepare `program` for distributed execution.

        `mode`:
          * "pserver" (implied by a non-empty `pservers` list): the
            reference workflow — optimizer ops move to per-endpoint
            pserver programs, the trainer program gains one fused send.
          * "spmd" (default otherwise): GSPMD-style mesh lowering — the
            program's sharding annotations (layers.shard /
            data(sharding=...)) are completed by parallel/spmd.py's
            propagation, validated (inconsistent specs raise HERE, at
            transpile time), and recorded as the placement plan
            `build_executor` lowers onto the mesh through the proven
            strategy executors: ParallelExecutor (dp × tp × ZeRO-1,
            optional bucketed-psum compute/collective overlap) or
            PipelineExecutor when the program carries pipeline_stage
            annotations and the mesh a 'pp' axis.

        `mesh` is an alias for `mesh_axes` ({axis: size}); `overlap`
        is the ParallelExecutor overlap mode for the spmd path."""
        from ..core.framework import default_main_program

        self._program = program or default_main_program()
        self._startup = startup_program or default_startup_program()
        if mesh_axes is None and mesh is not None:
            mesh_axes = mesh
        if mesh_axes is None:
            # reference-style arg mapping: `trainers` data-parallel workers
            mesh_axes = {"dp": trainers}
        self._mesh_axes = mesh_axes
        self._shard_opt = shard_optimizer_states
        self._endpoints = [e.strip() for e in (pservers or "").split(",")
                           if e.strip()]
        self._optimize_ops = list(optimize_ops or [])
        self._trainers = trainers
        self._sync_mode = sync_mode
        self._overlap = overlap
        self._batch_axis = batch_axis
        if mode is None:
            mode = "pserver" if self._endpoints else "spmd"
        if mode not in ("pserver", "spmd"):
            raise ValueError(f"mode must be 'pserver' or 'spmd', "
                             f"got {mode!r}")
        self._mode = mode
        if mode == "pserver":
            if self._endpoints and params_grads:
                self._transpile_pserver(list(params_grads), split_method)
            return
        self._transpile_spmd()

    def _transpile_spmd(self):
        """Record the mesh on the program desc, complete the sharding
        annotations, and fail fast on inconsistent specs — the spmd
        analogue of the reference transpiler's program rewrite (the
        'rewrite' is a placement plan: sharding is an execution
        property on a TPU mesh)."""
        from .spmd import propagate_sharding

        self._program.mesh_axes = {str(k): int(v)
                                   for k, v in self._mesh_axes.items()}
        self._program.bump_version()
        self._plan = propagate_sharding(
            self._program, mesh_axes=self._program.mesh_axes,
            batch_axis=self._batch_axis).check()

    # -- real pserver mode (multi-process CPU clusters / host-side path) ----
    def _transpile_pserver(self, params_grads, split_method=None):
        """Rewrite the trainer program: optimizer ops out, ONE fused
        send op in (reference distribute_transpiler.py:134-231;
        whole-param placement per a distributed_spliter policy, default
        balanced_split — size-weighted so no pserver owns nearly all
        the bytes; round_robin/hash_name stay selectable)."""
        from . import distributed_spliter

        if split_method is None:
            split_method = distributed_spliter.balanced_split
        eps = self._endpoints
        self._pairs_by_ep = {ep: [] for ep in eps}
        placement = split_method([p for p, _ in params_grads], eps)
        for (p, g), ep in zip(params_grads, placement):
            self._assign[p.name] = ep
            self._pairs_by_ep[ep].append((p, g))

        block = self._program.global_block()
        drop = set(id(op) for op in self._optimize_ops)
        block.ops[:] = [op for op in block.ops if id(op) not in drop]
        if params_grads:
            # one bucketed send across ALL endpoints: per-var epmap for
            # the grads, out_epmap for the param pulls.  The runtime
            # (ops/distributed.py + parallel/comm.py) packs each
            # endpoint's grads into arrival-order buckets and overlaps
            # endpoints; the per-endpoint send ops emitted before this
            # forced one serial round per pserver.
            block.append_op(
                "send",
                {"X": [g.name for _, g in params_grads]},
                {"Out": [p.name for p, _ in params_grads]},
                {"endpoints": list(eps),
                 "epmap": [self._assign[p.name]
                           for p, _ in params_grads],
                 "out_epmap": [self._assign[p.name]
                               for p, _ in params_grads]})
        self._program.bump_version()

    def get_trainer_program(self):
        return self._program

    def get_pserver_program(self, endpoint=None):
        """Build the per-endpoint pserver program: one listen_and_serv op
        whose sub-block holds the optimizer ops of the params assigned to
        this endpoint (reference distribute_transpiler.py:523-618).

        On a TPU mesh (no `pservers` given) there is no pserver role and
        the original program is returned for API parity."""
        if not self._endpoints:
            return self._program
        from ..core.framework import Program, program_guard
        from ..layers.io import ListenAndServ

        pairs = self._pairs_by_ep.get(endpoint, [])
        mine = {p.name for p, _ in pairs}
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            serv = ListenAndServ(endpoint, fan_in=self._trainers,
                                 sync_mode=getattr(self, "_sync_mode",
                                                   True))
            with serv.do():
                sub = prog.current_block
                for op in self._optimize_ops:
                    param_in = op.inputs.get("Param", [])
                    if param_in and param_in[0] not in mine:
                        continue
                    for n in (op.input_names() + op.output_names()):
                        if not sub.has_var(n):
                            src = self._find_var(n)
                            sub.create_var(
                                name=n,
                                shape=src.shape if src else None,
                                dtype=src.dtype if src else "float32",
                                persistable=True)
                    sub.append_op(op.type, dict(op.inputs),
                                  dict(op.outputs), dict(op.attrs))
        return prog

    def _find_var(self, name):
        for blk in self._program.blocks:
            if blk.has_var(name):
                return blk.var(name)
        return None

    def get_startup_program(self, endpoint=None, pserver_program=None):
        """The pserver process initializes params/accumulators/lr with the
        same startup program the trainer uses (values are then owned by
        the pserver; reference get_startup_program :620)."""
        return self._startup or default_startup_program()

    def build_executor(self, feed_names, fetch_list, startup_program=None,
                       **kw):
        """Lower the transpiled program onto the mesh.  In spmd mode
        this dispatches by program shape: pipeline_stage annotations +
        a 'pp' mesh axis go to PipelineExecutor (dp × pp × tp × sp, the
        GPipe/1F1B schedules), everything else to ParallelExecutor
        (dp × tp with ZeRO-1 and the bucketed-psum overlap) — the
        proven strategy implementations the MULTICHIP dryruns pin."""
        startup_program = startup_program or self._startup
        if self._mode == "spmd" and self._uses_pipeline():
            from .pipeline_program import PipelineExecutor

            mesh = dict(self._mesh_axes)
            kw.setdefault("tp_axis",
                          "tp" if mesh.get("tp", 1) > 1 else None)
            kw.setdefault("sp_axis",
                          "sp" if mesh.get("sp", 1) > 1 else None)
            kw.setdefault("batch_axis", self._batch_axis)
            kw.setdefault("shard_optimizer_states", self._shard_opt)
            return PipelineExecutor(
                self._program, feed_names, fetch_list, mesh=mesh,
                startup_program=startup_program, **kw)
        if self._mode == "spmd":
            kw.setdefault("overlap", self._overlap)
            kw.setdefault("spmd_plan", self._plan)
            kw.setdefault("batch_axis", self._batch_axis)
        kw.setdefault("shard_optimizer_states", self._shard_opt)
        return ParallelExecutor(
            self._program, feed_names, fetch_list,
            mesh=self._mesh_axes, startup_program=startup_program, **kw)

    def _uses_pipeline(self) -> bool:
        if not self._program or self._mesh_axes.get("pp", 1) <= 1:
            return False
        return any("pipeline_stage" in op.attrs
                   for op in self._program.global_block().ops)


class ShardingTranspiler(DistributeTranspiler):
    """The GSPMD-annotation entry point: `transpile(program=...,
    mesh={'dp': 2, 'pp': 2, 'tp': 2})` + `build_executor(...)` lowers
    a sharding-annotated Program onto the mesh (always mode='spmd';
    docs/performance.md 'Multichip sharding')."""

    def transpile(self, *args, **kw):
        kw.setdefault("mode", "spmd")
        if kw["mode"] != "spmd":
            raise ValueError("ShardingTranspiler is spmd-only — use "
                             "DistributeTranspiler for the pserver path")
        return super().transpile(*args, **kw)


class SimpleDistributeTranspiler(DistributeTranspiler):
    """Whole-variable placement variant (reference
    distribute_transpiler_simple.py:1-256).  The base class already places
    whole params (no block splitting), so this is the same transpiler under
    the reference's other name — kept so both entry points exist."""
