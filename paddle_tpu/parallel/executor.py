"""ParallelExecutor: SPMD execution of a Program over a device mesh.

This one component replaces ALL of the reference's parallelism machinery
(SURVEY.md §2.5):
  * `parallel_do_op` worker threads + per-place scopes + grad sum
    (/root/reference/paddle/fluid/operators/parallel_do_op.cc:113-346)
    -> batch dp-sharded into one jit; XLA splits the work per device.
  * NCCL allreduce ops (operators/nccl_op.cu.cc, doc/design/paddle_nccl.md)
    -> the gradient all-reduce is inserted BY XLA's sharding propagation
    (replicated params x dp-sharded batch), riding ICI.
  * DistributeTranspiler + gRPC pserver (distribute_transpiler.py:133,
    operators/listen_and_serv_op.cc) -> `shard_optimizer_states=True`
    partitions optimizer accumulators across the mesh (the pserver
    block-shard analogue, ZeRO-1 numerics == sync pserver SGD), with
    reduce-scatter/all-gather chosen by the compiler.
  * MultiGradientMachine ring (gserver/gradientmachines/MultiGradientMachine.h)
    -> same allreduce, no hand-rolled ring.

Tensor-parallel layers: pass `param_shardings={param_name: PartitionSpec}`
to split weight matrices over a 'tp'/'mp' axis; activations follow by
propagation.
"""
from __future__ import annotations

import itertools
import time
from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.executor import CPUPlace, Executor, program_to_fn
from ..core.framework import Variable, default_startup_program
from ..core.scope import Scope
from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing
from .checkpoint import ShardedCheckpointMixin
from .mesh import make_mesh

# same series as core.executor's run histogram (get-or-create by name),
# with a "pe<N>" instance label and mode="parallel"
_PE_IDS = itertools.count()
_M_RUN_SECONDS = obs_metrics.histogram(
    "paddle_tpu_executor_run_seconds",
    "Executor.run wall latency by execution mode", ("exe", "mode"))


def _amp_enabled() -> bool:
    from ..amp import is_bf16_enabled
    return is_bf16_enabled()


def _trace_flags() -> tuple:
    """Snapshot of every flag read at TRACE time by op lowerings (plus
    memory_optimize, which decides feed donation — part of the built
    executable); a jit built under one snapshot must not serve
    another."""
    from ..core.flags import get_flag
    return (_amp_enabled(), get_flag("flash_min_seq_k"),
            get_flag("flash_pack_heads"), get_flag("flash_block_q"),
            get_flag("flash_block_k"), get_flag("conv_layout"),
            get_flag("memory_optimize"))

__all__ = ["ParallelExecutor", "DistributeTranspiler",
           "SimpleDistributeTranspiler"]


class ParallelExecutor(ShardedCheckpointMixin):
    def __init__(
        self,
        program,
        feed_names: Sequence[str],
        fetch_list: Sequence,
        mesh,
        startup_program=None,
        batch_axis: str = "dp",
        param_shardings: Optional[Dict[str, P]] = None,
        shard_optimizer_states: bool = False,
        seed: int = 0,
    ):
        if isinstance(mesh, dict):
            mesh = make_mesh(mesh)
        self.mesh: Mesh = mesh
        self.batch_axis = batch_axis
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = [
            v.name if isinstance(v, Variable) else str(v)
            for v in fetch_list
        ]
        # PADDLE_TPU_VERIFY pre-flight, same contract as Executor.run
        # (gated inside preflight): a bad graph fails here in ms, not
        # minutes into the SPMD trace
        from ..analysis import preflight

        preflight(program, feed_names=self.feed_names,
                  fetch_names=self.fetch_names)
        self._fn = program_to_fn(program, self.feed_names, self.fetch_names)
        # explicit `donate=True` var hints fail HERE (build time) when
        # unsafe — e.g. a donated feed that is also fetched — not as a
        # deleted-buffer crash mid-train
        blk = program.global_block()
        hinted = [n for n in self.feed_names
                  if getattr(blk.vars.get(n), "donate", False)]
        if hinted:
            from ..memory_optimization_transpiler import plan_donation

            rw = [n for n in self._fn.state_in_names
                  if n in self._fn.state_out_names]
            plan_donation(program, self.feed_names, self.fetch_names,
                          state_rw_names=rw, requested=hinted).check()
        self._seed = seed
        self._step = 0
        param_shardings = dict(param_shardings or {})

        # --- initialize states on host, then place with shardings ---------
        startup = startup_program or default_startup_program()
        scope = Scope()
        Executor(CPUPlace()).run(startup, scope=scope)

        param_names = {
            v.name for v in program.global_block().all_parameters()
        }
        self._state_shardings = {}
        states = {}
        for n in self._fn.state_in_names:
            val = scope.find_var(n)
            if val is None:
                raise RuntimeError(
                    f"state var {n!r} not produced by the startup program")
            spec = self._spec_for(n, np.asarray(val), param_names,
                                  param_shardings,
                                  shard_optimizer_states)
            sh = NamedSharding(self.mesh, spec)
            states[n] = jax.device_put(np.asarray(val), sh)
            self._state_shardings[n] = sh
        self._states = states

        data_sh = NamedSharding(self.mesh, P(self.batch_axis))
        self._data_sharding = data_sh

        fn = self._fn

        def step(feeds, states, key):
            fetches, new_states = fn(feeds, states, key)
            return fetches, new_states

        self._step_fn = step
        self._jit_step = self._make_jit_step()
        self._trace_flags_state = _trace_flags()

    def _make_jit_step(self):
        # donation plan (memory_optimization_transpiler via
        # program_to_fn): states are donated always — `run` rebinds
        # self._states to the returned dict, so the old buffers die with
        # the step (ZeRO-style in-place update).  Feed buffers (always
        # freshly device_put from host in `run`) join under the
        # memory_optimize flag when the plan covers every feed — jit
        # donation is per-argument, and a fetched feed must survive.
        from ..core.flags import get_flag

        donate = [1]
        plan = self._fn.donation_plan
        if get_flag("memory_optimize") and \
                set(self.feed_names) <= plan.feeds:
            donate.insert(0, 0)
        return jax.jit(
            self._step_fn,
            out_shardings=(None, self._out_state_shardings()),
            donate_argnums=tuple(donate),
        )

    def _refresh_trace_flags(self):
        # trace-time flags (amp_bf16, flash_min_seq_k) are read inside op
        # lowerings; identical input avals would silently reuse an
        # executable traced under the old flag state, so any flip gets a
        # fresh jit cache (serial Executor: same flags in its cache keys)
        if _trace_flags() != self._trace_flags_state:
            self._jit_step = self._make_jit_step()
            self._trace_flags_state = _trace_flags()

    # -- sharding policy -----------------------------------------------------
    def _spec_for(self, name, val, param_names, param_shardings,
                  shard_opt) -> P:
        # explicit spec wins (params and their accumulators)
        for pname, spec in param_shardings.items():
            if name == pname:
                return spec
            if name.startswith(pname + "_") and name.endswith("_acc"):
                # accumulator inherits its parameter's sharding
                if tuple(val.shape) and len(spec) <= len(val.shape):
                    return spec
        if shard_opt and name.endswith("_acc") and val.ndim >= 1:
            # ZeRO-1 / pserver-shard analogue: split accumulator dim 0
            dp = self.mesh.shape[self.batch_axis]
            if val.shape[0] % dp == 0 and val.shape[0] >= dp:
                return P(self.batch_axis)
        return P()

    def _out_state_shardings(self):
        return {n: self._state_shardings[n]
                for n in sorted(set(self._fn.state_in_names)
                                | set(self._fn.state_out_names))
                if n in self._state_shardings} or None

    # -- execution -----------------------------------------------------------
    def run(self, feed: Dict, fetch_list=None, return_numpy=True):
        t0 = time.perf_counter()
        self._refresh_trace_flags()
        fetch_names = ([v.name if isinstance(v, Variable) else str(v)
                        for v in fetch_list]
                       if fetch_list is not None else self.fetch_names)
        assert fetch_names == self.fetch_names, \
            "fetch_list must match construction-time fetch_list"
        with obs_tracing.span("executor.run", mode="parallel"):
            feeds = {
                n: jax.device_put(np.asarray(v), self._data_sharding)
                for n, v in feed.items()
            }
            key = jax.random.fold_in(jax.random.key(self._seed),
                                     self._step)
            self._step += 1
            fetches, self._states = self._jit_step(feeds, self._states,
                                                   key)
            out = [fetches[n] for n in fetch_names]
            if return_numpy:
                out = [np.asarray(v) for v in out]
        if obs_metrics.enabled():
            if not hasattr(self, "_m_run"):
                self._m_run_id = f"pe{next(_PE_IDS)}"
                self._m_run = _M_RUN_SECONDS.labels(
                    exe=self._m_run_id, mode="parallel")
            self._m_run.observe(time.perf_counter() - t0)
        return out

    def close(self):
        """Reclaim this instance's registry series (per-instance
        telemetry contract: churned executors must not grow every
        metrics dump without bound).  The executor stays usable."""
        if hasattr(self, "_m_run"):
            _M_RUN_SECONDS.remove(exe=self._m_run_id, mode="parallel")

    def compiled_collectives(self, feed: Dict) -> Dict[str, int]:
        """Counts of cross-device collective ops in the optimized HLO of
        the train step compiled for `feed`'s shapes — pins the
        communication STRUCTURE of a mesh without the hardware (e.g.
        dp-N must show grad all-reduces and nothing else; run_scaling.py
        --virtual reports this per N alongside the no-op virtual
        throughput)."""
        from .mesh import count_collectives

        feeds = {
            n: jax.ShapeDtypeStruct(np.asarray(v).shape,
                                    np.asarray(v).dtype,
                                    sharding=self._data_sharding)
            for n, v in feed.items()
        }
        key = jax.random.key(self._seed)
        txt = self._jit_step.lower(feeds, self._states, key) \
            .compile().as_text()
        return count_collectives(txt)

    def state(self, name, return_numpy=True):
        v = self._states[name]
        return np.asarray(v) if return_numpy else v

    def set_state(self, name, value):
        self._states[name] = jax.device_put(
            np.asarray(value), self._state_shardings[name])


class DistributeTranspiler:
    """API-compatible entry point for the reference's transpiler workflow
    (/root/reference/python/paddle/v2/fluid/distribute_transpiler.py:133).

    The reference rewrites the program into trainer (split/send/concat) and
    per-pserver (listen_and_serv + optimize-block) programs.  On a TPU mesh
    none of that rewriting exists as program surgery: `transpile` records
    the mesh layout, `get_trainer_program` returns the ORIGINAL program
    (configuration-as-compilation — sharding is an execution property), and
    `build_executor` yields a ParallelExecutor where
      * grad aggregation = psum over the dp axis (was: send + fan-in barrier
        + sum at the pserver, listen_and_serv_op.cc:114-153)
      * optimizer-state sharding = ZeRO-1 accumulator partitioning (was:
        ~1024-element param blocks round-robined over pservers,
        distribute_transpiler.py:91-132)
    """

    def __init__(self):
        self._mesh_axes = None
        self._program = None
        self._startup = None
        self._shard_opt = True
        self._endpoints = []
        self._assign = {}          # param name -> endpoint
        self._pairs_by_ep = {}     # endpoint -> [(param, grad)]
        self._optimize_ops = []

    def transpile(self, optimize_ops=None, params_grads=None,
                  trainers=1, pservers: str = "", program=None,
                  startup_program=None,
                  mesh_axes: Optional[Dict[str, int]] = None,
                  shard_optimizer_states: bool = True,
                  split_method=None, sync_mode: bool = True):
        from ..core.framework import default_main_program

        self._program = program or default_main_program()
        self._startup = startup_program or default_startup_program()
        if mesh_axes is None:
            # reference-style arg mapping: `trainers` data-parallel workers
            mesh_axes = {"dp": trainers}
        self._mesh_axes = mesh_axes
        self._shard_opt = shard_optimizer_states
        self._endpoints = [e.strip() for e in (pservers or "").split(",")
                           if e.strip()]
        self._optimize_ops = list(optimize_ops or [])
        self._trainers = trainers
        self._sync_mode = sync_mode
        if self._endpoints and params_grads:
            self._transpile_pserver(list(params_grads), split_method)

    # -- real pserver mode (multi-process CPU clusters / host-side path) ----
    def _transpile_pserver(self, params_grads, split_method=None):
        """Rewrite the trainer program: optimizer ops out, ONE fused
        send op in (reference distribute_transpiler.py:134-231;
        whole-param placement per a distributed_spliter policy, default
        balanced_split — size-weighted so no pserver owns nearly all
        the bytes; round_robin/hash_name stay selectable)."""
        from . import distributed_spliter

        if split_method is None:
            split_method = distributed_spliter.balanced_split
        eps = self._endpoints
        self._pairs_by_ep = {ep: [] for ep in eps}
        placement = split_method([p for p, _ in params_grads], eps)
        for (p, g), ep in zip(params_grads, placement):
            self._assign[p.name] = ep
            self._pairs_by_ep[ep].append((p, g))

        block = self._program.global_block()
        drop = set(id(op) for op in self._optimize_ops)
        block.ops[:] = [op for op in block.ops if id(op) not in drop]
        if params_grads:
            # one bucketed send across ALL endpoints: per-var epmap for
            # the grads, out_epmap for the param pulls.  The runtime
            # (ops/distributed.py + parallel/comm.py) packs each
            # endpoint's grads into arrival-order buckets and overlaps
            # endpoints; the per-endpoint send ops emitted before this
            # forced one serial round per pserver.
            block.append_op(
                "send",
                {"X": [g.name for _, g in params_grads]},
                {"Out": [p.name for p, _ in params_grads]},
                {"endpoints": list(eps),
                 "epmap": [self._assign[p.name]
                           for p, _ in params_grads],
                 "out_epmap": [self._assign[p.name]
                               for p, _ in params_grads]})
        self._program.bump_version()

    def get_trainer_program(self):
        return self._program

    def get_pserver_program(self, endpoint=None):
        """Build the per-endpoint pserver program: one listen_and_serv op
        whose sub-block holds the optimizer ops of the params assigned to
        this endpoint (reference distribute_transpiler.py:523-618).

        On a TPU mesh (no `pservers` given) there is no pserver role and
        the original program is returned for API parity."""
        if not self._endpoints:
            return self._program
        from ..core.framework import Program, program_guard
        from ..layers.io import ListenAndServ

        pairs = self._pairs_by_ep.get(endpoint, [])
        mine = {p.name for p, _ in pairs}
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            serv = ListenAndServ(endpoint, fan_in=self._trainers,
                                 sync_mode=getattr(self, "_sync_mode",
                                                   True))
            with serv.do():
                sub = prog.current_block
                for op in self._optimize_ops:
                    param_in = op.inputs.get("Param", [])
                    if param_in and param_in[0] not in mine:
                        continue
                    for n in (op.input_names() + op.output_names()):
                        if not sub.has_var(n):
                            src = self._find_var(n)
                            sub.create_var(
                                name=n,
                                shape=src.shape if src else None,
                                dtype=src.dtype if src else "float32",
                                persistable=True)
                    sub.append_op(op.type, dict(op.inputs),
                                  dict(op.outputs), dict(op.attrs))
        return prog

    def _find_var(self, name):
        for blk in self._program.blocks:
            if blk.has_var(name):
                return blk.var(name)
        return None

    def get_startup_program(self, endpoint=None, pserver_program=None):
        """The pserver process initializes params/accumulators/lr with the
        same startup program the trainer uses (values are then owned by
        the pserver; reference get_startup_program :620)."""
        return self._startup or default_startup_program()

    def build_executor(self, feed_names, fetch_list, startup_program=None,
                       **kw) -> ParallelExecutor:
        return ParallelExecutor(
            self._program, feed_names, fetch_list,
            mesh=self._mesh_axes, startup_program=startup_program,
            shard_optimizer_states=self._shard_opt, **kw)


class SimpleDistributeTranspiler(DistributeTranspiler):
    """Whole-variable placement variant (reference
    distribute_transpiler_simple.py:1-256).  The base class already places
    whole params (no block splitting), so this is the same transpiler under
    the reference's other name — kept so both entry points exist."""
