"""Mixture-of-Experts with expert parallelism over an 'ep' mesh axis.

No reference analogue — the reference's closest machinery is the sparse
remote embedding (SURVEY.md §2.5: rows live on pservers, prefetched by
id).

Three execution forms share one gating implementation (`moe_gate`,
GShard/Switch dispatch-combine tensors, top-1 or top-2, static capacity,
fully differentiable — one-hot matmuls, no gathers on the backward
path):

  * `moe_dense(x, ...)` — mesh-free math: gating + batched expert
    matmuls as plain einsums.  This is what the DSL `layers.moe_ffn` op
    lowers to (single device or XLA-partitioned under ParallelExecutor
    with `param_shardings={w_in: P('ep'), ...}`), and the oracle the
    parallel forms are tested against.
  * `moe_ffn(x, ..., mesh)` — replicated routing, shard_map'd experts:
    the [T,E,C] dispatch/combine tensors materialize on every device
    (cheap at moderate T·E·C); only the [E,...] expert buffers are
    sharded.  Good when tokens-per-device is small.
  * `moe_ffn_a2a(x, ..., mesh)` — token-sharded routing with
    all_to_all dispatch (the GShard layout): each device gates its OWN
    T/n tokens, builds per-source capacity buffers [E, C_loc, D], and
    one all_to_all regroups them expert-major so each device runs its
    E/n experts on tokens from every source; a second all_to_all
    returns the outputs.  Memory per device is O(T/n · E · C_loc) —
    this is the form that scales T with the mesh.

Capacity semantics differ between the last two (global vs per-source
capacity) exactly as in GShard; with a non-overflowing capacity_factor
they are numerically identical (pinned in tests/test_moe.py).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map

__all__ = ["moe_gate", "moe_dense", "moe_ffn", "moe_ffn_a2a",
           "load_balance", "drop_rate"]


def moe_gate(x, gate_w, num_experts: int, capacity: int, top_k: int = 1):
    """Top-1 (Switch) or top-2 (GShard) gating.  x: [T, D]; gate_w: [D, E].

    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] weights,
    aux_loss scalar).  For top-2 the two gate values are renormalized to
    sum to 1 and second choices claim capacity only after ALL first
    choices (GShard's position rule), so a hot expert drops second
    choices first."""
    if top_k not in (1, 2):
        raise ValueError(f"top_k must be 1 or 2, got {top_k}")
    logits = x @ gate_w                                  # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx1 = jnp.argmax(probs, axis=-1)                    # [T]
    mask1 = jax.nn.one_hot(idx1, num_experts, dtype=jnp.float32)
    g1 = jnp.sum(probs * mask1, axis=-1)

    # position of each token within its expert's capacity buffer
    pos1 = jnp.sum((jnp.cumsum(mask1, axis=0) - 1.0) * mask1, axis=-1)
    keep1 = (pos1 < capacity).astype(jnp.float32)
    pos1_1h = jax.nn.one_hot(pos1.astype(jnp.int32), capacity,
                             dtype=jnp.float32)
    d1 = mask1[:, :, None] * pos1_1h[:, None, :] * keep1[:, None, None]

    # load-balance aux loss (Switch eq. 4): E * sum_e f_e * p_e, with
    # f_e the fraction of tokens whose FIRST choice is e
    frac_tokens = jnp.mean(mask1, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac_tokens * frac_probs)

    if top_k == 1:
        return d1, d1 * g1[:, None, None], aux

    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, num_experts, dtype=jnp.float32)
    g2 = jnp.sum(probs * mask2, axis=-1)
    # second choices are placed after every first choice of that expert
    first_count = jnp.sum(mask1, axis=0)                 # [E]
    pos2 = jnp.sum(((jnp.cumsum(mask2, axis=0) - 1.0)
                    + first_count[None, :]) * mask2, axis=-1)
    keep2 = (pos2 < capacity).astype(jnp.float32)
    pos2_1h = jax.nn.one_hot(pos2.astype(jnp.int32), capacity,
                             dtype=jnp.float32)
    d2 = mask2[:, :, None] * pos2_1h[:, None, :] * keep2[:, None, None]

    denom = jnp.maximum(g1 + g2, 1e-9)
    combine = (d1 * (g1 / denom)[:, None, None]
               + d2 * (g2 / denom)[:, None, None])
    return d1 + d2, combine, aux


def _capacity(T: int, E: int, capacity_factor: float, top_k: int) -> int:
    return max(1, int(capacity_factor * top_k * T / E))


def _expert_mm(inp, wi, wo, activation):
    """[*, C, D] tokens through per-expert FFNs [*, D, H] / [*, H, D] —
    batched dense matmuls -> MXU."""
    h = activation(jnp.einsum("...cd,...dh->...ch", inp, wi))
    return jnp.einsum("...ch,...hd->...cd", h, wo)


def moe_dense(x, gate_w, w_in, w_out, capacity_factor: float = 1.25,
              top_k: int = 1, activation=jax.nn.relu,
              capacity: int = None,
              selection=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mesh-free MoE FFN: the math every parallel form implements.
    x: [T, D]; returns (y [T, D], aux_loss).

    When the serving-kernel tier is armed (docs/performance.md
    "Serving kernels"), gate + capacity dispatch run as ONE Pallas
    kernel — same math, no [T, E, C] dispatch tensor in HBM;
    `selection` takes an existing kernels.registry.Selection for
    fallback-series ownership (defaults to a one-off pick)."""
    E = gate_w.shape[1]
    T = x.shape[0]
    if capacity is None:
        capacity = _capacity(T, E, capacity_factor, top_k)

    from ..kernels import registry as _kernel_registry

    picker = selection if selection is not None \
        else _kernel_registry.Selection()
    fused = picker.pick(
        "moe_gate_dispatch", tokens=int(T), d_model=int(x.shape[1]),
        num_experts=int(E), capacity=int(capacity), top_k=int(top_k),
        dtype=str(x.dtype))
    if fused is not None:
        expert_in_f, combine, aux2 = fused(x, gate_w)
        expert_in = expert_in_f.astype(x.dtype)
        aux = aux2[0, 0]
    else:
        dispatch, combine, aux = moe_gate(x, gate_w, E, capacity, top_k)
        expert_in = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                               dispatch).astype(x.dtype)
    expert_out = _expert_mm(expert_in, w_in, w_out, activation)
    y = jnp.einsum("ecd,tec->td", expert_out.astype(jnp.float32),
                   combine).astype(x.dtype)
    return y, aux


def moe_ffn(x, gate_w, w_in, w_out, mesh: Mesh, axis: str = "ep",
            capacity_factor: float = 1.25, top_k: int = 1,
            activation=jax.nn.relu) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel FFN, replicated routing (see module docstring).

    x: [T, D], gate_w: [D, E], w_in: [E, D, H], w_out: [E, H, D] with E
    divisible by the 'ep' axis size.  Returns (y [T, D], aux_loss)."""
    E = gate_w.shape[1]
    n = mesh.shape[axis]
    assert E % n == 0, f"experts {E} must divide ep axis {n}"
    T = x.shape[0]
    capacity = _capacity(T, E, capacity_factor, top_k)

    dispatch, combine, aux = moe_gate(x, gate_w, E, capacity, top_k)
    expert_in = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                           dispatch).astype(x.dtype)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis))
    def _experts(inp, wi, wo):
        return _expert_mm(inp, wi, wo, activation)

    expert_out = _experts(expert_in, w_in, w_out)        # [E, C, D]
    y = jnp.einsum("ecd,tec->td", expert_out.astype(jnp.float32),
                   combine).astype(x.dtype)
    return y, aux


def moe_ffn_a2a(x, gate_w, w_in, w_out, mesh: Mesh, axis: str = "ep",
                capacity_factor: float = 1.25, top_k: int = 1,
                activation=jax.nn.relu) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel FFN with token-sharded routing + all_to_all
    dispatch (the GShard layout; see module docstring).

    x: [T, D] with T divisible by the axis size; capacity is per
    (expert, source-shard): C_loc = capacity_factor * top_k * (T/n) / E,
    so a hot expert drops per-shard overflow locally before anything
    crosses the ICI.  Returns (y [T, D], mean aux_loss)."""
    E = gate_w.shape[1]
    n = mesh.shape[axis]
    assert E % n == 0, f"experts {E} must divide ep axis {n}"
    T = x.shape[0]
    assert T % n == 0, f"tokens {T} must divide ep axis {n}"
    c_loc = _capacity(T // n, E, capacity_factor, top_k)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P(axis)),
        out_specs=(P(axis), P()))
    def _run(x_blk, gw, wi, wo):
        dispatch, combine, aux = moe_gate(x_blk, gw, E, c_loc, top_k)
        # local capacity buffers per expert: [E, C_loc, D]
        buf = jnp.einsum("td,tec->ecd", x_blk.astype(jnp.float32),
                         dispatch).astype(x_blk.dtype)
        # all_to_all: split the expert dim across devices, concat the
        # source dim -> [E/n, n*C_loc, D] on each device
        buf = jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=1,
                                 tiled=True)
        out = _expert_mm(buf, wi, wo, activation)
        # route outputs back to their source shards
        out = jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                                 tiled=True)                 # [E, C_loc, D]
        y = jnp.einsum("ecd,tec->td", out.astype(jnp.float32),
                       combine).astype(x_blk.dtype)
        return y, jax.lax.pmean(aux, axis)

    return _run(x, gate_w, w_in, w_out)


def load_balance(x, gate_w) -> dict:
    """Routing diagnostics: per-expert first-choice token fractions and
    their max/mean ratio (1.0 = perfectly balanced)."""
    probs = jax.nn.softmax((x @ gate_w).astype(jnp.float32), axis=-1)
    frac = jnp.mean(jax.nn.one_hot(jnp.argmax(probs, -1),
                                   gate_w.shape[1]), axis=0)
    return {"frac": frac, "imbalance": jnp.max(frac) * gate_w.shape[1]}


def drop_rate(x, gate_w, capacity_factor: float = 1.25, top_k: int = 1,
              capacity: int = None, shards: int = 1) -> dict:
    """What static capacity actually costs at this routing state.

    An imbalanced router (load_balance imbalance > 1) overflows its hot
    experts' capacity buffers and the overflow tokens are DROPPED
    (their expert output is zero; the residual stream carries them) —
    the metric no artifact reported before r5.  Returns:
      assignment_drop  fraction of the T*top_k routing assignments that
                       lost their capacity slot
      weight_drop      fraction of total combine WEIGHT lost (second
                       choices carry less gate weight, so this is the
                       output-relevant number)
    `shards` > 1 evaluates per-source capacity (the moe_ffn_a2a layout:
    C_loc per shard, hot-expert overflow drops locally)."""
    E = gate_w.shape[1]
    T = x.shape[0]
    assert T % shards == 0, f"tokens {T} must divide shards {shards}"
    t_loc = T // shards
    cap = (_capacity(t_loc, E, capacity_factor, top_k)
           if capacity is None else capacity)
    assigned = kept = weight = weight_kept = 0.0
    for s in range(shards):
        xb = x[s * t_loc:(s + 1) * t_loc]
        dispatch, combine, _ = moe_gate(xb, gate_w, E, cap, top_k)
        probs = jax.nn.softmax((xb @ gate_w).astype(jnp.float32), -1)
        top = jax.lax.top_k(probs, top_k)[0]
        if top_k == 2:
            top = top / jnp.maximum(top.sum(-1, keepdims=True), 1e-9)
        assigned += t_loc * top_k
        kept += jnp.sum(dispatch)
        weight += jnp.sum(top)
        weight_kept += jnp.sum(combine)
    return {"capacity": cap,
            "assignment_drop": float(1.0 - kept / assigned),
            "weight_drop": float(1.0 - weight_kept / weight)}
