"""Mixture-of-Experts with expert parallelism over an 'ep' mesh axis.

No reference analogue — the reference's closest machinery is the sparse
remote embedding (SURVEY.md §2.5: rows live on pservers, prefetched by
id).

Design (Switch/GShard-style top-1 routing):
  * static capacity per expert (`capacity_factor`) keeps shapes static
    under jit; overflow tokens are dropped (their output is 0, the
    residual path carries them), underflow is padding.
  * gating and the dispatch/combine einsums run REPLICATED (the [T,E,C]
    routing tensors are materialized on every device — cheap at these
    contraction sizes); only the expert FFNs are sharded: shard_map
    slices the [E,C,D] expert buffer over the 'ep' axis and the XLA
    partitioner inserts the resulting collectives.
  * differentiable end-to-end: routing uses one-hot matmuls (no gather
    on the bwd path); an auxiliary load-balancing loss is returned.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["moe_ffn", "moe_gate"]


def moe_gate(x, gate_w, num_experts: int, capacity: int):
    """Top-1 (switch) gating.  x: [T, D]; gate_w: [D, E].

    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] weights,
    aux_loss scalar) — the GShard dispatch/combine tensor formulation,
    fully differentiable."""
    logits = x @ gate_w                                  # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)              # [T]
    expert_1h = jax.nn.one_hot(expert_idx, num_experts,
                               dtype=jnp.float32)        # [T, E]
    gate_val = jnp.sum(probs * expert_1h, axis=-1)       # [T]

    # position of each token within its expert's capacity buffer
    pos_in_expert = (jnp.cumsum(expert_1h, axis=0) - 1.0) * expert_1h
    pos = jnp.sum(pos_in_expert, axis=-1)                # [T]
    keep = (pos < capacity).astype(jnp.float32)          # overflow -> drop
    pos_1h = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=jnp.float32)           # [T, C]

    dispatch = expert_1h[:, :, None] * pos_1h[:, None, :] * \
        keep[:, None, None]                              # [T, E, C]
    combine = dispatch * gate_val[:, None, None]

    # load-balance aux loss (Switch Transformer eq. 4): E * sum_e f_e * p_e
    frac_tokens = jnp.mean(expert_1h, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def moe_ffn(x, gate_w, w_in, w_out, mesh: Mesh, axis: str = "ep",
            capacity_factor: float = 1.25,
            activation=jax.nn.relu) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel FFN layer.

    x: [T, D] tokens (T divisible by nothing in particular),
    gate_w: [D, E], w_in: [E, D, H], w_out: [E, H, D] with E divisible by
    the 'ep' axis size.  Only the expert FFNs are sharded (over `axis`);
    gating and the [T,E,C] dispatch/combine einsums run replicated, and
    XLA's partitioner inserts the ep-axis collectives around the expert
    matmuls (see the module docstring for the sizing implications).

    Returns (y [T, D], aux_loss)."""
    E = gate_w.shape[1]
    n = mesh.shape[axis]
    assert E % n == 0, f"experts {E} must divide ep axis {n}"
    T = x.shape[0]
    capacity = max(1, int(capacity_factor * T / E))

    dispatch, combine, aux = moe_gate(x, gate_w, E, capacity)
    # expert inputs: [E, C, D] (one-hot contraction — differentiable)
    expert_in = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                           dispatch).astype(x.dtype)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis))
    def _experts(inp, wi, wo):
        # inp: [E/n, C, D]; batched dense matmuls -> MXU
        h = activation(jnp.einsum("ecd,edh->ech", inp, wi))
        return jnp.einsum("ech,ehd->ecd", h, wo)

    expert_out = _experts(expert_in, w_in, w_out)        # [E, C, D]
    y = jnp.einsum("ecd,tec->td", expert_out.astype(jnp.float32),
                   combine).astype(x.dtype)
    return y, aux
