"""Collective ops + sharded embedding.

IR-level collectives (the analogue of the reference's NCCL op family,
/root/reference/paddle/fluid/operators/nccl_op.cc ncclAllReduce/ncclReduce/
ncclBcast, and the send/recv pserver path): registered as ordinary ops so
transpiled programs can express them; their lowerings call `jax.lax.p*`
primitives, valid when the block is executed under `shard_map` (spmd mode).

`sharded_embedding` is the large-model sparse-embedding capability
(reference: MAT_SPARSE_ROW_PREFETCH / SparseRowMatrix remote prefetch,
doc/design/cluster_train/large_model_dist_train.md): the table is
row-sharded over a mesh axis; lookups psum the per-shard partial gathers
(each shard contributes rows it owns), so only touched rows move — over ICI
instead of a pserver RPC.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.execution import data_of, one
from .mesh import shard_map
from ..core.registry import register_op

__all__ = ["sharded_embedding_lookup", "shard_embedding_table"]


@register_op("c_allreduce_sum", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": "dp"})
def c_allreduce_sum(ctx, ins, attrs):
    return {"Out": jax.lax.psum(data_of(one(ins, "X")), attrs["ring_id"])}


@register_op("c_allreduce_mean", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": "dp"})
def c_allreduce_mean(ctx, ins, attrs):
    return {"Out": jax.lax.pmean(data_of(one(ins, "X")), attrs["ring_id"])}


@register_op("c_allreduce_max", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": "dp"})
def c_allreduce_max(ctx, ins, attrs):
    return {"Out": jax.lax.pmax(data_of(one(ins, "X")), attrs["ring_id"])}


@register_op("c_allgather", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": "dp", "axis": 0})
def c_allgather(ctx, ins, attrs):
    return {"Out": jax.lax.all_gather(
        data_of(one(ins, "X")), attrs["ring_id"],
        axis=attrs.get("axis", 0), tiled=True)}


@register_op("c_reducescatter", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": "dp", "axis": 0})
def c_reducescatter(ctx, ins, attrs):
    return {"Out": jax.lax.psum_scatter(
        data_of(one(ins, "X")), attrs["ring_id"],
        scatter_dimension=attrs.get("axis", 0), tiled=True)}


@register_op("c_broadcast", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": "dp", "root": 0})
def c_broadcast(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    idx = jax.lax.axis_index(attrs["ring_id"])
    root_val = jax.lax.psum(
        jnp.where(idx == attrs.get("root", 0), x, jnp.zeros_like(x)),
        attrs["ring_id"])
    return {"Out": root_val}


@register_op("c_ppermute", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": "dp", "shift": 1})
def c_ppermute(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    axis = attrs["ring_id"]
    # psum of the literal 1 is the static axis size on every jax
    # version (jax.lax.axis_size is newer than the floor we support)
    n = int(jax.lax.psum(1, axis))
    s = attrs.get("shift", 1)
    perm = [(j, (j + s) % n) for j in range(n)]
    return {"Out": jax.lax.ppermute(x, axis, perm)}


# ---------------------------------------------------------------------------
# sharded embedding
# ---------------------------------------------------------------------------


def shard_embedding_table(mesh: Mesh, table, axis: str = "mp"):
    """Place an embedding table row-sharded over `axis`."""
    return jax.device_put(table, NamedSharding(mesh, P(axis)))


def sharded_embedding_lookup(ids, table, mesh: Mesh, axis: str = "mp"):
    """ids: [n] int32 global; table: [vocab, dim] row-sharded over `axis`.
    Each shard gathers the ids it owns (others contribute zeros) and a psum
    over `axis` assembles full vectors."""
    vocab = table.shape[0]
    n_shards = mesh.shape[axis]
    rows_per = vocab // n_shards

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(axis, None)),
        out_specs=P())
    def _lookup(ids_l, tbl_l):
        shard = jax.lax.axis_index(axis)
        lo = shard * rows_per
        local = ids_l - lo
        owned = (local >= 0) & (local < rows_per)
        safe = jnp.clip(local, 0, rows_per - 1)
        vecs = jnp.take(tbl_l, safe, axis=0)
        vecs = jnp.where(owned[:, None], vecs, jnp.zeros_like(vecs))
        return jax.lax.psum(vecs, axis)

    return _lookup(ids, table)


def sharded_embedding_grad(ids, grad_out, vocab, mesh: Mesh,
                           axis: str = "mp"):
    """Scatter per-row grads back to the owning shards (SelectedRows ->
    shard-local dense scatter-add), returning a row-sharded dense grad."""
    n_shards = mesh.shape[axis]
    rows_per = vocab // n_shards

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P()),
        out_specs=P(axis, None))
    def _scatter(ids_l, g_l):
        shard = jax.lax.axis_index(axis)
        lo = shard * rows_per
        local = ids_l - lo
        owned = (local >= 0) & (local < rows_per)
        safe = jnp.clip(local, 0, rows_per - 1)
        g = jnp.where(owned[:, None], g_l, jnp.zeros_like(g_l))
        return jnp.zeros((rows_per, g_l.shape[1]), g_l.dtype
                         ).at[safe].add(g)

    return _scatter(ids, grad_out)
