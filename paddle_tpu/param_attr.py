"""ParamAttr / WeightNormParamAttr — structured parameter attributes.

Reference: /root/reference/python/paddle/v2/fluid/param_attr.py (ParamAttr
:1-87, WeightNormParamAttr :90-104).  Layers here accept plain dicts for
parameter attributes; ParamAttr subclasses dict so both spellings work
interchangeably.  WeightNormParamAttr triggers the weight-normalization
reparameterization w = g * v / ||v|| (Salimans & Kingma) in
LayerHelper.create_parameter, matching the reference's
_create_weight_normalize (layer_helper.py:107-304).
"""
from __future__ import annotations

__all__ = ["ParamAttr", "WeightNormParamAttr"]


class ParamAttr(dict):
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, gradient_clip=None,
                 do_model_average=None, update_hooks=None):
        super().__init__()
        if name is not None:
            self["name"] = name
        if initializer is not None:
            self["initializer"] = initializer
        self["learning_rate"] = learning_rate
        if regularizer is not None:
            self["regularizer"] = regularizer
        self["trainable"] = trainable
        if gradient_clip is not None:
            self["gradient_clip_attr"] = gradient_clip
        if do_model_average is not None:
            self["do_model_average"] = do_model_average
        if update_hooks is not None:
            self["update_hooks"] = update_hooks


class WeightNormParamAttr(ParamAttr):
    """`dim`: the dimension KEPT by the norm (g has shape [shape[dim]];
    None means one scalar g over the whole tensor), as in the reference."""

    # Reference API note (param_attr.py:100): the reference tracks the
    # reparameterized outputs in this class-level list; here they are
    # tracked per-Program (`program.params_with_weight_norm`) so old
    # programs can be garbage-collected.  This list stays for import
    # compatibility and is intentionally never grown.
    params_with_weight_norm = []

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
        self["weight_norm_dim"] = -1 if dim is None else int(dim)
