"""TTL-lease service registry: elastic pserver membership + liveness.

Python surface over the native implementation
(`native/src/registry.cc`).  Reference semantics:
go/pserver/etcd_client.go — a pserver `Register`s under the lowest free
index with a TTL lease kept alive by heartbeats and publishes its
address; trainers discover the live address list and wait for the
desired count (go/pserver/client/etcd_client.go); an expired lease frees
the index so a replacement claims it (go/cmd/pserver/pserver.go:34-45).

Use `Registry` to host (in-process handle + optional TCP serving) and
`RegistryClient` from other processes.  `Lease` runs the heartbeat loop
on a daemon thread and exposes `lost` when the registry revoked the
slot (e.g. after a heartbeat gap longer than the TTL).
"""
from __future__ import annotations

import atexit
import ctypes
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from paddle_tpu import native
from paddle_tpu.core.resilience import RetryPolicy

__all__ = ["Registry", "RegistryClient", "Lease"]


def _declare(lib):
    if getattr(lib, "_registry_declared", False):
        return lib
    p = ctypes.c_void_p
    i = ctypes.c_int
    i64 = ctypes.c_int64
    cp = ctypes.c_char_p
    lib.pt_registry_create.restype = p
    lib.pt_registry_create.argtypes = []
    lib.pt_registry_set_desired.argtypes = [p, cp, i]
    lib.pt_registry_register.restype = i
    lib.pt_registry_register.argtypes = [
        p, cp, cp, ctypes.c_double, ctypes.POINTER(i64)]
    lib.pt_registry_heartbeat.restype = i
    lib.pt_registry_heartbeat.argtypes = [p, cp, i, i64]
    lib.pt_registry_deregister.restype = i
    lib.pt_registry_deregister.argtypes = [p, cp, i, i64]
    lib.pt_registry_list.restype = ctypes.c_size_t
    lib.pt_registry_list.argtypes = [p, cp, cp, ctypes.c_size_t]
    lib.pt_registry_wait_ready.restype = i
    lib.pt_registry_wait_ready.argtypes = [
        p, cp, ctypes.c_size_t, ctypes.c_double]
    lib.pt_registry_serve.restype = i
    lib.pt_registry_serve.argtypes = [p, i]
    lib.pt_registry_stop.argtypes = [p]
    lib.pt_registry_destroy.argtypes = [p]
    lib._registry_declared = True
    return lib


class Registry:
    """In-process registry; `serve()` additionally exposes it over TCP."""

    def __init__(self):
        self._lib = _declare(native.lib())
        self._h = self._lib.pt_registry_create()
        self.port: Optional[int] = None

    def set_desired(self, kind: str, n: int) -> None:
        self._lib.pt_registry_set_desired(self._h, kind.encode(), n)

    def register(self, kind: str, addr: str,
                 ttl_s: float) -> Tuple[int, int]:
        """(index, lease) or raises when all desired slots are held."""
        lease = ctypes.c_int64(0)
        idx = self._lib.pt_registry_register(
            self._h, kind.encode(), addr.encode(), ttl_s,
            ctypes.byref(lease))
        if idx < 0:
            raise RuntimeError(
                f"registry: no free {kind!r} slot below the desired count")
        return idx, lease.value

    def heartbeat(self, kind: str, index: int, lease: int) -> bool:
        if not self._h:  # closed registry: definitive GONE, not a crash
            return False
        return bool(self._lib.pt_registry_heartbeat(
            self._h, kind.encode(), index, lease))

    def deregister(self, kind: str, index: int, lease: int) -> bool:
        if not self._h:  # releasing a lease after close() must be safe
            return False
        return bool(self._lib.pt_registry_deregister(
            self._h, kind.encode(), index, lease))

    def list(self, kind: str) -> Dict[int, str]:
        if not self._h:
            return {}
        # pt_registry_list returns the required length; retry bigger on
        # truncation rather than silently dropping endpoints
        size = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(size)
            need = self._lib.pt_registry_list(
                self._h, kind.encode(), buf, len(buf))
            if need < len(buf):
                break
            size = max(size * 2, need + 1)
        out: Dict[int, str] = {}
        for line in buf.value.decode().splitlines():
            if line.strip():
                idx, addr = line.split(None, 1)
                out[int(idx)] = addr
        return out

    def wait_ready(self, kind: str, n: int, timeout_s: float) -> bool:
        return bool(self._lib.pt_registry_wait_ready(
            self._h, kind.encode(), n, timeout_s))

    def serve(self, port: int = 0) -> int:
        got = self._lib.pt_registry_serve(self._h, port)
        if got < 0:
            raise RuntimeError("registry: TCP serve failed")
        self.port = got
        return got

    def stop(self) -> None:
        if self._h:
            self._lib.pt_registry_stop(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.pt_registry_stop(self._h)
            self._lib.pt_registry_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class RegistryClient:
    """TCP client; one short-lived connection per call (the protocol is
    line-oriented and every verb is a single round trip).

    Transient transport failures (registry restarting, socket hiccup
    mid-heartbeat) retry through a RetryPolicy instead of surfacing as a
    raw OSError with no backoff; knobs are env-tunable via
    ``PADDLE_TPU_REGISTRY_RETRY_*`` (core/resilience.py).  The default
    budget is deliberately short — a heartbeat that backs off past the
    TTL is as lost as one that failed — and a RetryError still IS an
    OSError, so Lease._beat's keep-retrying loop semantics hold."""

    def __init__(self, addr: str, timeout_s: float = 5.0,
                 retry_policy: Optional[RetryPolicy] = None):
        host, port = addr.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout_s
        self.policy = retry_policy or RetryPolicy.from_env(
            "REGISTRY_RETRY", max_attempts=3, base_delay=0.05,
            max_delay=0.5, deadline=5.0)

    def _roundtrip(self, line: str, multi: bool = False) -> List[str]:
        return self.policy.call(
            lambda: self._roundtrip_once(line, multi),
            what=(f"registry at {self._addr[0]}:{self._addr[1]}: "
                  f"{line.split()[0]} failed"))

    def _connect_retrying(self, what: str) -> socket.socket:
        """A connected socket, retrying ONLY the connect phase through
        the policy.  For non-idempotent verbs (REG): once a request
        line may have reached the registry, a lost reply must surface
        instead of causing a re-send — a duplicate REG mints a ghost
        slot whose lease nobody heartbeats, and its TTL expiry later
        reads as a spurious member death."""
        return self.policy.call(
            lambda: socket.create_connection(self._addr,
                                             timeout=self._timeout),
            what=(f"registry at {self._addr[0]}:{self._addr[1]}: "
                  f"{what} failed"))

    def _roundtrip_once(self, line: str, multi: bool = False,
                        sock: Optional[socket.socket] = None) -> List[str]:
        with (sock or socket.create_connection(
                self._addr, timeout=self._timeout)) as s:
            s.sendall(line.encode() + b"\n")
            f = s.makefile("r")
            first = f.readline().strip()
            if not first:
                # clean EOF before a reply (registry restarting / closing
                # the accept): a TRANSIENT transport failure, not a
                # protocol answer — callers like Lease._beat retry on
                # OSError but treat a definitive GONE as revocation
                raise OSError(f"registry closed connection mid-request "
                              f"({line.split()[0]})")
            if not multi:
                return [first]
            lines = [first]
            while True:
                ln = f.readline()
                if not ln or ln.strip() == ".":
                    break
                lines.append(ln.rstrip("\n"))
            return lines

    def set_desired(self, kind: str, n: int) -> None:
        self._roundtrip(f"DESIRE {kind} {n}")

    def register(self, kind: str, addr: str,
                 ttl_s: float) -> Tuple[int, int]:
        # NOT via _roundtrip: REG is the one non-idempotent verb, so
        # only its connect retries (_connect_retrying docstring)
        resp = self._roundtrip_once(
            f"REG {kind} {int(ttl_s * 1000)} {addr}",
            sock=self._connect_retrying("REG connect"))[0].split()
        if resp[0] != "OK":
            raise RuntimeError(
                f"registry: no free {kind!r} slot below the desired count")
        return int(resp[1]), int(resp[2])

    def heartbeat(self, kind: str, index: int, lease: int) -> bool:
        return self._roundtrip(f"HB {kind} {index} {lease}")[0] == "OK"

    def deregister(self, kind: str, index: int, lease: int) -> bool:
        return self._roundtrip(f"DEREG {kind} {index} {lease}")[0] == "OK"

    def list(self, kind: str) -> Dict[int, str]:
        lines = self._roundtrip(f"LIST {kind}", multi=True)
        out: Dict[int, str] = {}
        for line in lines[1:]:
            if line.strip():
                idx, addr = line.split(None, 1)
                out[int(idx)] = addr
        return out

    def wait_ready(self, kind: str, n: int, timeout_s: float) -> bool:
        # server blocks up to the REMAINING window; allow socket slack
        # on top.  Transport failures retry like every other verb, but
        # each retry asks the server only for what is left of the
        # caller's timeout_s — a hiccup mid-wait cannot stretch the
        # call to ~2x the requested bound
        host, port = self._addr
        deadline = time.monotonic() + timeout_s
        state = self.policy.begin()
        while True:
            left = max(0.0, deadline - time.monotonic())
            sent = False
            try:
                with socket.create_connection(
                        (host, port),
                        timeout=left + self._timeout) as s:
                    s.sendall(
                        f"WAIT {kind} {n} "
                        f"{int(left * 1000)}\n".encode())
                    sent = True
                    return s.makefile("r").readline().strip() == "OK"
            except OSError as e:
                if sent and time.monotonic() < deadline:
                    # the request reached the server, so the failure
                    # came AFTER time legitimately spent blocked in the
                    # server-side wait — that time must not be charged
                    # against the policy's (short) failure deadline, or
                    # one hiccup late in a long WAIT aborts instead of
                    # retrying the remaining window
                    state = self.policy.begin()
                state.record(e, what=(f"registry at {host}:{port}: "
                                      "WAIT failed"))
                state.sleep()


class Lease:
    """Holds one registration alive: heartbeats every ttl/3 on a daemon
    thread; `lost` flips when the registry revoked the slot (missed
    heartbeats past the TTL — the reference's lease-expiry signal that
    tells a pserver to exit, go/cmd/pserver/pserver.go:42)."""

    def __init__(self, registry, kind: str, addr: str, ttl_s: float = 3.0,
                 on_lost=None):
        self._reg = registry
        self.kind = kind
        self.addr = addr
        self.ttl_s = ttl_s
        self.index, self._lease = registry.register(kind, addr, ttl_s)
        self.lost = False
        self.released = False
        self._on_lost = on_lost
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._beat, daemon=True)
        self._thread.start()
        # a cleanly-exiting member frees its slot IMMEDIATELY instead of
        # making the cluster wait out the TTL (and the controller treat
        # a normal exit as a failure); release() is idempotent, so an
        # explicit release beats the hook to it and unregisters it
        atexit.register(self.release)

    def _beat(self):
        while not self._stop.wait(self.ttl_s / 3.0):
            try:
                ok = self._reg.heartbeat(self.kind, self.index, self._lease)
            except OSError:
                continue  # registry unreachable: retry until it answers
            if not ok:  # definitive GONE: the slot was revoked
                self.lost = True
                if self._on_lost is not None:
                    self._on_lost()
                return

    def release(self):
        """Stop heartbeating and free the slot.  Idempotent, and safe
        after the registry is gone (closed handle, dead TCP peer,
        interpreter teardown) — a release can never raise."""
        if self.released:
            return
        self.released = True
        try:
            atexit.unregister(self.release)
        except Exception:  # interpreter teardown ordering
            pass
        self._stop.set()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=self.ttl_s)
        try:
            self._reg.deregister(self.kind, self.index, self._lease)
        except Exception:
            # OSError (registry unreachable / RetryError) or native
            # teardown artifacts: the TTL reclaims the slot anyway
            pass


def resolve_pserver_cluster(ttl_s: float = 3.0, timeout_s: float = 60.0,
                            exit_on_lost: bool = True):
    """Role-aware cluster resolution for registry-launched pserver jobs
    (tools/launch.py --registry): replaces the static PSERVERS endpoint
    list with TTL-lease discovery (reference go/pserver etcd flow).

    Reads PADDLE_TPU_REGISTRY (+PADDLE_TPU_NUM_PSERVERS, TRAINING_ROLE).
    A PSERVER first BINDS a listening socket (parked for its upcoming
    listen_and_serv via `parallel.pserver.prebind_endpoint` — the port
    is owned continuously from publication to serve, no TOCTOU gap),
    registers the bound address under a kept-alive lease, then everyone
    blocks until the desired count is registered and gets the SAME
    index-ordered endpoint list (the transpiler's param split is
    positional, so order must agree across all processes).

    `exit_on_lost` (pserver role): when the registry revokes the lease
    (heartbeat gap > TTL — the slot may already be re-assigned), the
    process EXITS instead of serving as a zombie with a stale identity,
    matching the reference pserver's lease-expiry crash
    (go/cmd/pserver/pserver.go:42).

    Returns (pservers_csv, my_endpoint_or_None, lease_or_None); falls
    back to the PSERVERS/SERVER_ENDPOINT env convention when no registry
    is configured.
    """
    import os
    import sys

    reg_addr = os.environ.get("PADDLE_TPU_REGISTRY")
    role = os.environ.get("TRAINING_ROLE", "TRAINER")
    if not reg_addr:
        return (os.environ["PSERVERS"],
                os.environ.get("SERVER_ENDPOINT"), None)
    rc = RegistryClient(reg_addr)
    n = int(os.environ["PADDLE_TPU_NUM_PSERVERS"])
    my_ep = None
    lease = None
    if role == "PSERVER":
        from ..parallel.pserver import prebind_endpoint

        my_ep = prebind_endpoint()

        def _lost():
            sys.stderr.write(
                f"pserver {my_ep}: registry lease revoked (heartbeat "
                "gap > TTL); exiting — the slot may already belong to a "
                "replacement\n")
            os._exit(17)

        lease = Lease(rc, "pserver", my_ep, ttl_s=ttl_s,
                      on_lost=_lost if exit_on_lost else None)
    if not rc.wait_ready("pserver", n, timeout_s):
        raise RuntimeError(
            f"registry at {reg_addr}: only "
            f"{len(rc.list('pserver'))}/{n} pservers registered within "
            f"{timeout_s}s — cluster cannot form (fail fast, don't hang)")
    eps = [addr for _, addr in sorted(rc.list("pserver").items())]
    return ",".join(eps), my_ep, lease
