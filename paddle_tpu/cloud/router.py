"""Multi-replica generation front door: discovery, placement, retry,
hot swap.

The cluster runtime (cloud/cluster.py) made TRAINING membership a
runtime property; this module does the same for SERVING.  Replicas
(serving/replica.ReplicaServer around one GenerationServer each)
register in the front door's TTL-lease registry under kind
"generation" — the same liveness machinery pservers use — and the
router:

* **discovers** the live replica set from the registry (a SIGKILLed
  replica's lease expires within one TTL and it drops out of the
  routing table; an explicit connection failure demotes it immediately
  instead of waiting out the TTL);
* **places** each request on the live replica with the LEAST
  outstanding tokens (prompt+max_new reserved at dispatch, released as
  tokens stream back) — queue-depth-aware load balancing, the
  Triton/TF-Serving instance-group idea applied across processes;
* **retries on replica death** through a RetryPolicy: decode is
  deterministic per (prompt, seed), so the survivor regenerates the
  same stream and the router resumes it with `skip` = tokens already
  delivered — the client sees no duplicate and no gap, just latency;
  policy sheds (deadline/saturation) are answers, never retried;
* **hot-swaps checkpoints with zero downtime**: replicas are swapped
  ONE AT A TIME (drain -> swap -> resume, serving/generation.py), and
  while one drains the router routes around it, so capacity dips by a
  single replica but availability never does.

Run `python -m paddle_tpu.cli serve MODEL_DIR --registry HOST:PORT`
per replica and point ReplicaRouter at the same registry (or let the
router host it: ``ReplicaRouter(desired=N)`` + pass
``router.registry_addr`` to the replicas).  docs/serving.md has the
runbook.
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Dict, List, Optional

from paddle_tpu.core.resilience import RetryPolicy
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import tracing as obs_tracing
from paddle_tpu.serving.batching import (RequestDeadlineExceeded,
                                         ServerSaturated)
from paddle_tpu.serving.generation import GenerationStream
from paddle_tpu.serving.replica import (ReplicaError, ReplicaShed,
                                        replica_call, replica_stream)

__all__ = ["ReplicaRouter", "NoReplicasAvailable"]

_LOG = logging.getLogger("paddle_tpu.router")

# one label per router instance (like GenerationServer's `server`):
# a process that churns routers must not mix their stats or grow dumps
_ROUTER_IDS = itertools.count()
_M_REQUESTS = obs_metrics.counter(
    "paddle_tpu_serving_router_requests_total",
    "front-door requests by outcome (ok/shed/failed)",
    ("router", "outcome"), always=True)
_M_RETRIES = obs_metrics.counter(
    "paddle_tpu_serving_router_retries_total",
    "request re-dispatches after a replica failure", ("router",),
    always=True)
_M_LIVE = obs_metrics.gauge(
    "paddle_tpu_serving_router_replicas_live",
    "replicas currently in the routing table", ("router",))
_M_SWAPS = obs_metrics.counter(
    "paddle_tpu_serving_router_swaps_total",
    "per-replica checkpoint hot swaps orchestrated", ("router",),
    always=True)
# always=True like the request counters: signals() (the autoscaler
# feed) is a stats()-style API whose contract must not depend on the
# metrics switch
_M_LATENCY = obs_metrics.histogram(
    "paddle_tpu_serving_router_request_seconds",
    "end-to-end front-door request latency (submit to last token, "
    "retries included)", ("router",), always=True)
_M_OUTSTANDING = obs_metrics.gauge(
    "paddle_tpu_serving_router_outstanding_tokens",
    "tokens reserved on replicas for in-flight requests", ("router",),
    always=True)


class NoReplicasAvailable(ConnectionError):
    """No live replica could serve the request within the retry
    budget (all dead, all demoted, or the registry lists none)."""


class _Replica:
    __slots__ = ("addr", "outstanding", "swapping")

    def __init__(self, addr: str):
        self.addr = addr
        self.outstanding = 0
        self.swapping = False


class ReplicaRouter:
    """The serving front door over a TTL-lease replica registry.

    Pass ``registry_addr`` to join an existing registry (e.g. a
    ClusterController's), or neither to let the router HOST one —
    ``router.registry_addr`` is then what each replica's
    ``cli serve --registry`` should point at.  ``desired`` caps the
    replica slots the hosted registry hands out."""

    def __init__(self, registry_addr: Optional[str] = None,
                 kind: str = "generation", desired: int = 16,
                 refresh_s: float = 0.2, demote_s: float = 3.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 request_timeout_s: float = 120.0):
        from .registry import Registry, RegistryClient

        self._kind = kind
        self._owned_registry = None
        if registry_addr is None:
            self._owned_registry = Registry()
            self._owned_registry.set_desired(kind, desired)
            port = self._owned_registry.serve(0)
            registry_addr = f"127.0.0.1:{port}"
        self.registry_addr = registry_addr
        self._rc = RegistryClient(registry_addr)
        self._refresh_s = float(refresh_s)
        self._demote_s = float(demote_s)
        self._timeout_s = float(request_timeout_s)
        self.policy = retry_policy or RetryPolicy.from_env(
            "ROUTER_RETRY", max_attempts=8, base_delay=0.05,
            max_delay=0.5, deadline=30.0)
        self._lock = threading.Lock()
        self._replicas: Dict[str, _Replica] = {}
        self._demoted: Dict[str, float] = {}
        # addresses an operator/autoscaler marked as draining: still
        # live (their in-flight streams keep completing) but excluded
        # from placement so the drain converges.  Keyed by address, not
        # _Replica, so the mark survives table re-lists and applies to
        # replicas not yet discovered.
        self._draining: set = set()
        self._last_refresh = 0.0
        self._closed = False
        rid = self._rid = str(next(_ROUTER_IDS))
        self._m_ok = _M_REQUESTS.labels(router=rid, outcome="ok")
        self._m_shed = _M_REQUESTS.labels(router=rid, outcome="shed")
        self._m_failed = _M_REQUESTS.labels(router=rid, outcome="failed")
        self._m_retries = _M_RETRIES.labels(router=rid)
        self._m_live = _M_LIVE.labels(router=rid)
        self._m_swaps = _M_SWAPS.labels(router=rid)
        self._m_latency = _M_LATENCY.labels(router=rid)
        self._m_outstanding = _M_OUTSTANDING.labels(router=rid)
        # windowed self-observation (ROADMAP 4's autoscaler substrate):
        # a TimeSeriesStore sampling this process's registry, started
        # lazily by watch()/signals() — the router then consumes
        # p99(window)/qps(window) instead of raw instantaneous gauges
        self._series = None

    # -- routing table ------------------------------------------------------
    def _refresh(self, force: bool = False):
        """Re-list the registry and merge into the routing table.  The
        NETWORK roundtrip runs outside the router lock: a slow registry
        (its client retries up to ~5s) must never stall the per-token
        accounting of every in-flight stream."""
        with self._lock:
            now = time.monotonic()
            if not force and now - self._last_refresh < self._refresh_s:
                return
            self._last_refresh = now  # claim the slot before the I/O
        try:
            listed = set(self._rc.list(self._kind).values())
        except OSError:
            return  # registry hiccup: keep routing on the last table
        with self._lock:
            now = time.monotonic()
            for addr in listed:
                if addr not in self._replicas:
                    self._replicas[addr] = _Replica(addr)
            for addr in list(self._replicas):
                if addr not in listed:
                    del self._replicas[addr]
                    # a retired replica's drain mark must not outlive
                    # it: the same host:port may serve a future replica
                    self._draining.discard(addr)
            # a demotion outlives the TTL only if the registry still
            # lists the member; expire stale demotions so a RESTARTED
            # replica on the same address gets traffic again
            for addr, until in list(self._demoted.items()):
                if now >= until:
                    del self._demoted[addr]
            if obs_metrics.enabled():
                self._m_live.set(len([a for a in self._replicas
                                      if a not in self._demoted]))

    def _pick_locked(self) -> Optional[_Replica]:
        live = [r for a, r in self._replicas.items()
                if a not in self._demoted and not r.swapping
                and a not in self._draining]
        if not live:
            return None
        return min(live, key=lambda r: r.outstanding)

    def _demote(self, addr: str):
        with self._lock:
            self._demoted[addr] = time.monotonic() + self._demote_s
            self._last_refresh = 0.0  # force a re-list on next pick

    def set_draining(self, addr: str, draining: bool = True) -> None:
        """Mark/unmark one replica as draining: it stays in the table
        (its in-flight streams keep their per-token accounting) but
        receives no new placements.  The autoscaler marks its scale-in
        victim before sending the replica `drain` verb so the router
        converges instead of racing fresh requests onto it."""
        with self._lock:
            if draining:
                self._draining.add(addr)
            else:
                self._draining.discard(addr)

    def live_replicas(self, include_draining: bool = True,
                      refresh: bool = True) -> List[str]:
        """Registry-live replica addresses (demotions excluded).  The
        autoscaler's capacity/invariant checks pass
        ``include_draining=False``: a draining replica still answers
        its accepted streams but is no longer serving capacity.
        ``refresh=False`` reads the table as of the last re-list — for
        a caller that just forced one and wants a second view of the
        SAME listing instead of another registry round-trip."""
        if refresh:
            self._refresh(force=True)
        with self._lock:
            return sorted(a for a in self._replicas
                          if a not in self._demoted
                          and (include_draining
                               or a not in self._draining))

    # -- request path -------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int, *,
               temperature: float = 0.0, seed: int = 0,
               eos_id: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> GenerationStream:
        """Route one generation request; returns a streaming future
        (serving.GenerationStream).  Tokens stream as the replica
        produces them; a replica death mid-stream is retried on a
        survivor transparently (resumed, never duplicated)."""
        prompt = [int(t) for t in list(prompt_ids)]
        stream = GenerationStream(prompt, max_new_tokens)
        req = {"op": "generate", "prompt": prompt,
               "max_new": int(max_new_tokens),
               "temperature": float(temperature), "seed": int(seed),
               "eos_id": eos_id, "deadline_ms": deadline_ms}
        expires = (time.monotonic() + deadline_ms / 1000.0
                   if deadline_ms is not None else None)
        t = threading.Thread(target=self._run_request,
                             args=(stream, req, expires), daemon=True)
        t.start()
        return stream

    def generate(self, prompt_ids, max_new_tokens: int,
                 timeout: Optional[float] = None, **kw) -> List[int]:
        return self.submit(prompt_ids, max_new_tokens, **kw).result(
            timeout or self._timeout_s)

    def _run_request(self, stream: GenerationStream, req: dict,
                     expires: Optional[float]):
        # root span of the fleet-wide request trace: the replica and
        # its generation server's phases parent under it through the
        # wire-propagated context, and the latency observe below runs
        # with it active, so the histogram exemplar names this trace
        with obs_tracing.span("router.request",
                              max_new=req["max_new"]):
            inj = obs_tracing.inject()
            if inj:
                req = dict(req, trace=inj)
            self._run_request_traced(stream, req, expires)

    def _run_request_traced(self, stream: GenerationStream, req: dict,
                            expires: Optional[float]):
        delivered = 0
        t_start = time.monotonic()
        state = self.policy.begin()
        while True:
            if expires is not None and time.monotonic() >= expires:
                self._m_shed.inc()
                stream._fail(RequestDeadlineExceeded(
                    "request deadline expired at the router"))
                return
            self._refresh()
            with self._lock:
                replica = self._pick_locked()
                if replica is not None:
                    reserve = req["max_new"] - delivered
                    replica.outstanding += reserve
            if replica is not None:
                self._m_outstanding.inc(reserve)
            if replica is None:
                try:
                    state.record(NoReplicasAvailable(
                        f"no live {self._kind!r} replicas in "
                        f"{self.registry_addr}"),
                        what="router: no replicas")
                    state.sleep()
                    continue
                except OSError as e:
                    self._m_failed.inc()
                    stream._fail(e)
                    return
            addr = replica.addr
            try:
                attempt = dict(req, skip=delivered)
                if expires is not None:
                    attempt["deadline_ms"] = max(
                        0.0, (expires - time.monotonic()) * 1000.0)
                for tok in replica_stream(addr, attempt,
                                          timeout_s=self._timeout_s):
                    delivered += 1
                    with self._lock:
                        replica.outstanding -= 1
                        reserve -= 1
                    self._m_outstanding.dec()
                    stream._put(tok)
                self._m_ok.inc()
                self._m_latency.observe(time.monotonic() - t_start)
                stream._finish()
                return
            except (ReplicaShed, ServerSaturated) as e:
                # a policy answer: the replica chose to shed — honor it
                self._m_shed.inc()
                stream._fail(e)
                return
            except ReplicaError as e:
                if e.fatal:
                    self._m_failed.inc()
                    stream._fail(e)
                    return
                exc: Exception = e
            except (OSError, ValueError) as e:
                # died mid-stream / unreachable / garbled frame
                exc = e
            finally:
                with self._lock:
                    replica.outstanding -= max(reserve, 0)
                self._m_outstanding.dec(max(reserve, 0))
            self._demote(addr)
            self._m_retries.inc()
            _LOG.warning("router: replica %s failed (%r), retrying "
                         "with %d/%d tokens delivered", addr, exc,
                         delivered, req["max_new"])
            try:
                state.record(exc, what=f"replica {addr} failed")
                state.sleep()
            except OSError as e:
                self._m_failed.inc()
                stream._fail(e)
                return

    # -- control plane ------------------------------------------------------
    def ping(self, addr: str) -> dict:
        return replica_call(addr, {"op": "ping"}, timeout_s=5.0)

    def swap(self, model_dir: str, timeout_s: float = 120.0) -> int:
        """Zero-downtime checkpoint hot swap across the fleet: each
        replica drains and swaps ONE AT A TIME while the router routes
        around it.  Returns the number of replicas swapped; raises if
        no replica could be swapped."""
        swapped = 0
        errors = []
        for addr in self.live_replicas():
            with self._lock:
                rep = self._replicas.get(addr)
                if rep is None:
                    continue
                rep.swapping = True
            try:
                ans = replica_call(addr, {"op": "swap", "dir": model_dir,
                                          "timeout": timeout_s},
                                   timeout_s=timeout_s + 10)
                if ans.get("ok"):
                    swapped += 1
                    self._m_swaps.inc()
                else:
                    errors.append((addr, ans.get("err", "swap refused")))
                    self._demote(addr)
            except OSError as e:
                errors.append((addr, repr(e)))
                self._demote(addr)
            finally:
                with self._lock:
                    rep = self._replicas.get(addr)
                    if rep is not None:
                        rep.swapping = False
        if not swapped:
            raise RuntimeError(
                f"hot swap installed on 0 replicas: {errors}")
        if errors:
            _LOG.warning("router: hot swap skipped %d replica(s): %s",
                         len(errors), errors)
        return swapped

    # -- windowed self-observation (the autoscaler substrate) ---------------
    def watch(self, period_s: float = 0.5, capacity: int = 720):
        """Start (idempotently) the router's time-series sampler and
        return the TimeSeriesStore.  This is the watchable
        queue-depth/latency history ROADMAP item 4's autoscaler scales
        on — windowed signals, not instantaneous gauge reads."""
        from paddle_tpu.observability.timeseries import TimeSeriesStore

        with self._lock:
            if self._closed:
                # a watch() racing close() must not resurrect a
                # sampler thread nobody will ever stop
                raise RuntimeError("router is closed")
            if self._series is None:
                self._series = TimeSeriesStore(period_s=period_s,
                                               capacity=capacity)
                self._series.start()
            return self._series

    def signals(self, window_s: float = 60.0) -> dict:
        """The scaling signals over one window: request rate, windowed
        p50/p99 latency (bucket-delta quantiles, NaN before traffic),
        reserved-token backlog, live replica count.  A scale-out
        policy reads `p99`/`qps`/`outstanding_tokens`; `replicas_live`
        closes its feedback loop."""
        series = self.watch()
        lbl = {"router": self._rid}
        return {
            "window_s": float(window_s),
            "qps": series.rate(
                "paddle_tpu_serving_router_requests_total", window_s,
                labels=lbl),
            "p50": series.p50(
                "paddle_tpu_serving_router_request_seconds", window_s,
                labels=lbl),
            "p99": series.p99(
                "paddle_tpu_serving_router_request_seconds", window_s,
                labels=lbl),
            "outstanding_tokens": series.latest(
                "paddle_tpu_serving_router_outstanding_tokens",
                labels=lbl),
            # as-of-last-re-list: a gauge in a SIGNAL summary must not
            # cost a forced registry round-trip per read (the autoscaler
            # polls signals() right after its own forced listing; the
            # request path re-lists on every pick anyway)
            "replicas_live": len(self.live_replicas(refresh=False)),
        }

    def stats(self) -> dict:
        with self._lock:
            return {
                "replicas": {a: r.outstanding
                             for a, r in self._replicas.items()},
                "demoted": sorted(self._demoted),
                "draining": sorted(self._draining),
                "requests_ok": int(self._m_ok.value),
                "requests_shed": int(self._m_shed.value),
                "requests_failed": int(self._m_failed.value),
                "retries": int(self._m_retries.value)}

    def close(self):
        if self._closed:
            return
        self._closed = True
        with self._lock:
            series, self._series = self._series, None
        if series is not None:
            series.stop()
        if self._owned_registry is not None:
            self._owned_registry.close()
        # reclaim this instance's registry series (router churn must
        # not grow dumps or bleed counts into later instances)
        for outcome in ("ok", "shed", "failed"):
            _M_REQUESTS.remove(router=self._rid, outcome=outcome)
        for fam in (_M_RETRIES, _M_LIVE, _M_SWAPS, _M_LATENCY,
                    _M_OUTSTANDING):
            fam.remove(router=self._rid)
