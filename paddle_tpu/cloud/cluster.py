"""Elastic cluster runtime: membership-driven rebalancing + recovery.

Reference: the Go cloud layer's fault-tolerant control plane
(go/master/service.go task re-dispatch, go/pserver/etcd_client.go TTL
leases, doc/design/cluster_train/README.md "trainers and pservers may
join and leave at any time").  PR 1 made a single process recoverable
and PR 5 made comm rounds fast; this module makes the CLUSTER SHAPE a
runtime property:

* **ClusterController** — watches the TTL-lease registry
  (cloud/registry.py) for pserver/trainer join and lease-expiry events
  and publishes epoch-numbered **ClusterView**s (member list +
  parameter placement + sync fan-in).  On a pserver membership change
  it re-runs ``distributed_spliter.balanced_split`` over the surviving
  endpoints and migrates parameter shards over the PR 5 batch wire
  (``PUT_BATCH``), sourcing a dead member's shards from its latest
  snapshot (parallel/checkpoint.latest_pserver_shard) or, failing
  that, from a trainer-held copy pushed during the transition.  Every
  transition is fenced: ``FENCE`` quiesces the optimize machinery on
  all live pservers, migration runs against frozen state, ``COMMIT``
  adopts the view — no optimize step mixes old and new placements.
* **ClusterClient** — the subscriber surface for trainers and tools:
  resolves/watches views, registers members (``join``), and answers
  the controller's trainer-held-recovery requests by pushing local
  parameter copies straight to the new owner pservers.

The trainer data path picks views up through ``parallel.comm``'s
process-global subscriber (``comm.set_cluster`` / the
``PADDLE_TPU_CONTROLLER`` env var): the fused send op re-derives each
round's endpoint map from the current view and, when a round dies
mid-flight (SIGKILLed pserver), waits for the next stable view and
retries against the new placement without a process restart.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from paddle_tpu.core.resilience import RetryPolicy, fault_injector
from paddle_tpu.observability import metrics as obs_metrics

from .registry import Lease, Registry, RegistryClient

__all__ = ["ClusterView", "ClusterController", "ClusterClient"]

_LOG = logging.getLogger("paddle_tpu.cluster")

_M_VIEW_EPOCH = obs_metrics.gauge(
    "paddle_tpu_cluster_view_epoch",
    "epoch of the controller's current published cluster view")
_M_MEMBERSHIP = obs_metrics.counter(
    "paddle_tpu_cluster_membership_changes_total",
    "membership events folded into a published view, by member kind",
    ("kind", "event"))
_M_REBALANCES = obs_metrics.counter(
    "paddle_tpu_cluster_rebalances_total",
    "completed fence->migrate->commit view changes")
_M_REBALANCE_SECONDS = obs_metrics.histogram(
    "paddle_tpu_cluster_rebalance_seconds",
    "wall time of one view change (fence + shard migration + commit)")
_M_MIGRATION_BYTES = obs_metrics.counter(
    "paddle_tpu_cluster_shard_migration_bytes_total",
    "serialized parameter bytes moved between pservers by rebalances")


class ClusterView:
    """One epoch-numbered snapshot of the cluster: who is in it, where
    every parameter lives, and how many trainers a sync round fans in.

    ``status``: "forming" (not enough members / no var defs yet),
    "rebalancing" (transition published so trainers can push
    trainer-held copies of ``needed`` shards), "stable"."""

    __slots__ = ("epoch", "status", "pservers", "trainers", "placement",
                 "fan_in", "needed", "registry")

    def __init__(self, epoch=0, status="forming", pservers=None,
                 trainers=None, placement=None, fan_in=None, needed=(),
                 registry=""):
        self.epoch = int(epoch)
        self.status = status
        self.pservers: Dict[int, str] = dict(pservers or {})
        self.trainers: Dict[int, str] = dict(trainers or {})
        self.placement: Dict[str, str] = dict(placement or {})
        self.fan_in = fan_in
        self.needed = list(needed)
        self.registry = registry

    @property
    def endpoints(self) -> List[str]:
        return [ep for _, ep in sorted(self.pservers.items())]

    def to_json(self) -> str:
        return json.dumps({
            "epoch": self.epoch, "status": self.status,
            "pservers": sorted(self.pservers.items()),
            "trainers": sorted(self.trainers.items()),
            "placement": self.placement, "fan_in": self.fan_in,
            "needed": self.needed, "registry": self.registry,
        }, separators=(",", ":"))

    @classmethod
    def from_json(cls, text: str) -> "ClusterView":
        d = json.loads(text)
        return cls(epoch=d["epoch"], status=d["status"],
                   pservers={int(i): ep for i, ep in d["pservers"]},
                   trainers={int(i): a for i, a in d["trainers"]},
                   placement=d["placement"], fan_in=d["fan_in"],
                   needed=d.get("needed", ()),
                   registry=d.get("registry", ""))

    def __repr__(self):
        return (f"ClusterView(epoch={self.epoch}, {self.status}, "
                f"pservers={self.endpoints}, "
                f"trainers={len(self.trainers)}, "
                f"vars={len(self.placement)})")


def _pserver_client(endpoint: str):
    """Controller-side pserver connection: short patience — a member
    that cannot answer within seconds is treated as dead and the
    rebalance recomputes without it (the TTL would evict it anyway)."""
    from ..parallel.pserver import VariableClient

    return VariableClient(
        endpoint, client_id=f"cluster-ctl-{os.getpid()}",
        connect_timeout=5.0, request_timeout=15.0,
        retry_policy=RetryPolicy(max_attempts=2, base_delay=0.1,
                                 max_delay=0.5, deadline=5.0))


class _MemberDied(Exception):
    def __init__(self, endpoint):
        super().__init__(endpoint)
        self.endpoint = endpoint


class ClusterController:
    """Watches membership, publishes views, orchestrates rebalances.

    ``var_descs``: the parameters under placement —
    ``distributed_spliter.VarDesc`` tuples (or anything with
    name/shape/dtype), settable at construction or later over the wire
    (``DEFINE`` — the first trainer to connect typically defines them
    from its transpiled program).  ``snapshot_dirs`` maps a pserver
    INDEX (the stable slot number) to that shard's snapshot directory,
    or is a callable ``index -> dir``; it is the recovery source for a
    member that died without a live copy.  ``master`` (optional
    cloud.Master) gets poked when a trainer lease expires so its
    lazy task-timeout reclaim runs promptly."""

    def __init__(self, registry: Optional[Registry] = None,
                 registry_addr: Optional[str] = None,
                 var_descs: Optional[Sequence] = None,
                 min_pservers: int = 1, split_method=None,
                 poll_s: float = 0.25, push_timeout_s: float = 10.0,
                 snapshot_dirs=None, master=None,
                 track_trainers: bool = True,
                 quarantine_s: float = 5.0):
        self._own_registry = None
        if registry is None and registry_addr is None:
            registry = Registry()
            registry.serve(0)
            self._own_registry = registry
        if registry is not None:
            self._reg = registry
            port = getattr(registry, "port", None)
            self.registry_addr = f"127.0.0.1:{port}" if port else ""
        else:
            self._reg = RegistryClient(registry_addr)
            self.registry_addr = registry_addr
        self.min_pservers = int(min_pservers)
        self.poll_s = float(poll_s)
        self.push_timeout_s = float(push_timeout_s)
        self.snapshot_dirs = snapshot_dirs or {}
        self.master = master
        self.track_trainers = track_trainers
        from ..parallel import distributed_spliter as spliter

        self._split = split_method or spliter.balanced_split
        self._vars = list(var_descs or [])
        self._lock = threading.Condition()
        self._view = ClusterView(registry=self.registry_addr)
        self._last_stable: Optional[ClusterView] = None
        self._needed: set = set()
        # (index, addr) pairs excluded mid-rebalance -> re-admit time.
        # A member that keeps its lease but cannot complete a
        # transition (a pre-elastic binary ERRing on FENCE, a wedged
        # process) would otherwise re-trigger a full fence+commit cycle
        # EVERY poll tick — each commit wiping in-flight grad slots on
        # the healthy members.  Quarantined pairs are filtered from the
        # registry listing for `quarantine_s`, bounding the churn to
        # one retry per window while still re-admitting a member that
        # recovers (or rejoins under a fresh lease).
        self.quarantine_s = float(quarantine_s)
        self._quarantine: Dict[tuple, float] = {}
        self._pclients: Dict[str, object] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._sock: Optional[socket.socket] = None
        self.port: Optional[int] = None

    # -- lifecycle ----------------------------------------------------------
    def serve(self, port: int = 0) -> int:
        """Start the view-protocol TCP server; returns the bound port."""
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self.port

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self):
        """Start the membership watch thread (serve() first if remote
        processes need the view protocol)."""
        t = threading.Thread(target=self._watch, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def define(self, var_descs: Sequence):
        """Set the placed-variable descs (idempotent: first definition
        wins — every process derives them from the same program)."""
        with self._lock:
            if not self._vars:
                self._vars = list(var_descs)

    def stop(self):
        self._stop.set()
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        with self._lock:
            self._lock.notify_all()
        # join the watch/serve threads BEFORE draining clients: a tick
        # mid-rebalance would otherwise reconnect and re-insert fresh
        # pserver clients after the drain, leaking their sockets.  The
        # joins are bounded — a thread stuck in a slow network op is
        # drained under popitem below rather than waited out forever
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=10.0)
        # popitem, not iteration: a straggler thread that outlived its
        # join timeout may still be inserting/popping clients —
        # mutating a dict being iterated raises and would abort
        # close() before the owned registry is torn down
        while True:
            try:
                _, c = self._pclients.popitem()
            except KeyError:
                break
            try:
                c.close()
            except Exception:
                pass

    def close(self):
        self.stop()
        if self._own_registry is not None:
            self._own_registry.close()
            self._own_registry = None

    # -- view access --------------------------------------------------------
    def view(self) -> ClusterView:
        with self._lock:
            return self._view

    def wait_view(self, min_epoch: int,
                  timeout_s: float = 30.0) -> Optional[ClusterView]:
        """Block until a STABLE view with epoch >= min_epoch is
        published (or timeout -> None)."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while not (self._view.status == "stable"
                       and self._view.epoch >= min_epoch):
                left = deadline - time.monotonic()
                if left <= 0 or self._stop.is_set():
                    return None
                self._lock.wait(timeout=min(left, 0.1))
            return self._view

    def _publish(self, view: ClusterView):
        with self._lock:
            self._view = view
            if view.status == "stable":
                # migration sourcing reads THIS view, not whatever was
                # last published: an all-dead stall or an interrupted
                # transition publishes intermediate views whose
                # pserver->index map no longer says where shards live
                self._last_stable = view
            self._lock.notify_all()
        _M_VIEW_EPOCH.set(view.epoch)

    # -- membership watch ---------------------------------------------------
    def _list(self, kind: str) -> Dict[int, str]:
        return dict(self._reg.list(kind))

    def _watch(self):
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception:
                # the watcher must survive anything — a transient
                # registry outage or an injected fault is a skipped
                # tick, not a dead control plane
                _LOG.warning("cluster watch tick failed", exc_info=True)
            self._stop.wait(self.poll_s)

    def _tick(self):
        ps = self._list("pserver")
        tr = self._list("trainer") if self.track_trainers else {}
        if self._quarantine:
            now = time.monotonic()
            self._quarantine = {k: t for k, t in
                                self._quarantine.items() if t > now}
            ps = {i: a for i, a in ps.items()
                  if (i, a) not in self._quarantine}
        with self._lock:
            view = self._view
            have_vars = bool(self._vars)
        if view.status == "forming":
            if len(ps) >= self.min_pservers and have_vars:
                self._rebalance(ps, tr)
            return
        if ps != view.pservers or tr != view.trainers:
            # ANY departed (index, addr) pair means a trainer is gone —
            # a bare subset check would miss a leave+join landing in
            # the same poll (or an expired slot re-registered)
            departed = set(view.trainers.items()) - set(tr.items())
            if self.master is not None and departed:
                # a trainer lease expired: poke the master so its lazy
                # task-timeout check runs now and orphaned task chunks
                # re-dispatch as soon as timeout_s allows
                try:
                    self.master.reclaim_expired()
                except Exception:
                    _LOG.warning("master reclaim poke failed",
                                 exc_info=True)
            self._rebalance(ps, tr)

    # -- rebalance (fence -> migrate -> commit) -----------------------------
    def _rebalance(self, ps: Dict[int, str], tr: Dict[int, str]):
        # a member that fails mid-rebalance is dropped from the target
        # membership and the whole transition recomputes — its shards
        # then source from snapshot/trainer copies like any dead member
        for _ in range(3):
            try:
                return self._rebalance_once(dict(ps), dict(tr))
            except _MemberDied as e:
                _LOG.warning(
                    "rebalance: pserver %s died mid-transition; "
                    "recomputing without it", e.endpoint)
                self._forget_client(e.endpoint)
                # quarantine the pair(s): a member that keeps
                # heartbeating but cannot transition must not re-enter
                # the target membership on the very next tick
                until = time.monotonic() + self.quarantine_s
                for i, ep in ps.items():
                    if ep == e.endpoint:
                        self._quarantine[(i, ep)] = until
                ps = {i: ep for i, ep in ps.items() if ep != e.endpoint}
        _LOG.error("rebalance: gave up after repeated member deaths")

    def _client(self, endpoint: str):
        c = self._pclients.get(endpoint)
        if c is None:
            c = self._pclients[endpoint] = _pserver_client(endpoint)
        return c

    def _forget_client(self, endpoint: str):
        c = self._pclients.pop(endpoint, None)
        if c is not None:
            try:
                c.close()
            except Exception:
                pass

    def _rebalance_once(self, ps: Dict[int, str], tr: Dict[int, str]):
        t0 = time.perf_counter()
        fault_injector().fire("cluster.rebalance")
        old = self.view()
        epoch = old.epoch + 1
        eps = [ep for _, ep in sorted(ps.items())]
        if not eps:
            # every pserver is gone: publish a non-stable view so
            # trainers BLOCK (and keep pushing nothing) until a
            # replacement registers, instead of erroring against
            # ghosts.  The LAST KNOWN placement rides along — it is
            # what the next rebalance reads to know which (dead)
            # endpoint owned each shard, so snapshot/trainer-held
            # recovery still runs when a replacement joins.
            _LOG.error("rebalance: no live pservers; cluster is stalled "
                       "until one joins")
            self._count_membership(old, ps, tr)
            self._publish(ClusterView(
                epoch=epoch, status="rebalancing", pservers={},
                trainers=tr, placement=old.placement,
                fan_in=len(tr) or None, registry=self.registry_addr))
            return
        fan_in = len(tr) if tr else None
        if old.status == "stable" and ps == old.pservers:
            # trainer-only churn: same endpoints + same vars means the
            # deterministic split cannot move a shard, so skip the
            # fence/migrate/drop machinery — one COMMIT per pserver
            # adopts the new fan-in (and releases any round stuck
            # behind a dead trainer's missing barrier)
            for ep in eps:
                try:
                    self._client(ep).commit(epoch, fan_in)
                except (OSError, ConnectionError, RuntimeError):
                    raise _MemberDied(ep)
            self._count_membership(old, ps, tr)
            self._publish(ClusterView(
                epoch=epoch, status="stable", pservers=ps, trainers=tr,
                placement=old.placement, fan_in=fan_in,
                registry=self.registry_addr))
            _M_REBALANCES.inc()
            _M_REBALANCE_SECONDS.observe(time.perf_counter() - t0)
            _LOG.info("cluster view %d committed (trainer-only): "
                      "%d pservers, %d trainers", epoch, len(ps),
                      len(tr))
            return
        from ..parallel.distributed_spliter import placement_map

        placement = placement_map(self._vars, eps, self._split)

        # phase 1: fence every live pserver (quiesce optimize rounds).
        # RuntimeError is a protocol-level ERR reply (e.g. a
        # pre-elastic server in the registry): treated like a death so
        # one incompatible member is excluded loudly instead of
        # wedging the watch loop in endless failed rebalances
        for ep in eps:
            try:
                self._client(ep).fence(epoch)
            except (OSError, ConnectionError, RuntimeError):
                raise _MemberDied(ep)

        # migrate shards: group by source/destination so transfers ride
        # the bucketed batch wire.  Sourcing uses the last STABLE view —
        # `old` may be an all-dead stall or a half-done transition whose
        # placement/index map does not say where shards actually live.
        src = self._last_stable if self._last_stable is not None else old
        needed = set(self._migrate(src, placement, set(eps)))

        # verify REALITY before trusting src any further: a retried
        # transition may already have moved or dropped shards in ways
        # no published view records, and on the initial placement a
        # bootstrap copy may sit on a non-owner (transpile-time layout
        # vs registration-order skew).  Probe every live member (HAVE),
        # move stray copies onto their placed owners, and fold
        # lost-everywhere previously-placed vars into the trainer-held
        # recovery set.  `owner_ok` gates the drop phase below — a copy
        # is only ever dropped once its placed owner is CONFIRMED to
        # hold the var, so no sequence of failures can erase the last
        # copy.
        owner_ok = self._consolidate(placement, eps, src, needed)

        if needed:
            # trainer-held recovery: publish the transition so
            # subscribers push their local copies of the lost shards to
            # the new owners (ClusterClient._push_needed), then wait
            with self._lock:
                self._needed = set(needed)
            self._publish(ClusterView(
                epoch=epoch, status="rebalancing", pservers=ps,
                trainers=tr, placement=placement, fan_in=fan_in,
                needed=sorted(needed), registry=self.registry_addr))
            deadline = time.monotonic() + self.push_timeout_s
            with self._lock:
                while self._needed and time.monotonic() < deadline \
                        and not self._stop.is_set():
                    self._lock.wait(timeout=0.1)
                left = sorted(self._needed)
                self._needed = set()
            owner_ok |= needed - set(left)  # pushed straight to owners
            # last resort for the un-pushed remainder: re-initialize to
            # zeros on the new owners — but ONLY names the owner holds
            # no copy of at all (owner_ok): a stale bootstrap copy that
            # no trainer refreshed still beats zeros, and a var the
            # owner never held would fail every GET and wedge the
            # cluster, which is strictly worse than zeros.
            truly_missing = [n for n in left if n not in owner_ok]
            if truly_missing:
                owner_ok |= self._zero_fill(truly_missing, placement)

        # drop non-owned copies so every param has ONE authoritative
        # home (and a later rebalance knows where to read it).  Only
        # copies whose placed owner is CONFIRMED to hold the var
        # (probe, migration, push, or zero-fill — `owner_ok`) are
        # dropped, and only vars the controller has PLACED before: on
        # the initial placement a bootstrap copy sitting on a
        # non-owner may be the ONLY copy.  Either gate alone keeps a
        # sequence of interrupted transitions from erasing the last
        # copy of a shard.
        drops: Dict[str, list] = {}
        for name, owner in placement.items():
            if name not in src.placement or name not in owner_ok:
                continue
            for ep in eps:
                if ep != owner:
                    drops.setdefault(ep, []).append(name)
        for ep, names in drops.items():
            try:
                self._client(ep).drop_vars(names)
            except (OSError, ConnectionError, RuntimeError):
                raise _MemberDied(ep)

        # phase 2: commit everywhere, then publish the stable view.
        # Membership is counted HERE, once per committed transition — a
        # _MemberDied retry re-enters this method, and counting at the
        # top would tally the same join/leave two or three times.
        for ep in eps:
            try:
                self._client(ep).commit(epoch, fan_in)
            except (OSError, ConnectionError, RuntimeError):
                raise _MemberDied(ep)
        self._count_membership(old, ps, tr)
        self._publish(ClusterView(
            epoch=epoch, status="stable", pservers=ps, trainers=tr,
            placement=placement, fan_in=fan_in,
            registry=self.registry_addr))
        _M_REBALANCES.inc()
        _M_REBALANCE_SECONDS.observe(time.perf_counter() - t0)
        _LOG.info("cluster view %d committed: %d pservers, %d trainers, "
                  "%d vars placed", epoch, len(ps), len(tr),
                  len(placement))

    def _zero_fill(self, names, placement: Dict[str, str]) -> set:
        """Install zeros on the placed owners.  Returns the names
        actually installed (unfillable ones — no known shape — are
        not)."""
        import numpy as np

        descs = {getattr(v, "name", None): v for v in self._vars}
        by_dst: Dict[str, list] = {}
        unfillable = []
        for name in names:
            d = descs.get(name)
            shape = tuple(getattr(d, "shape", ()) or ())
            if not shape or any(int(s) <= 0 for s in shape):
                unfillable.append(name)
                continue
            try:
                val = np.zeros(shape, dtype=str(getattr(
                    d, "dtype", "float32") or "float32"))
            except TypeError:
                val = np.zeros(shape, dtype="float32")
            by_dst.setdefault(placement[name], []).append((name, val))
        filled = sorted(set(names) - set(unfillable))
        if filled:
            _LOG.warning(
                "rebalance: no snapshot or trainer copy for %s — "
                "re-initialized to ZEROS on the new owners (learned "
                "values lost)", filled)
        if unfillable:
            _LOG.error(
                "rebalance: no recovery source AND no known shape for "
                "%s — reads of these will fail until some trainer "
                "pushes a copy", unfillable)
        for ep, pairs in by_dst.items():
            try:
                self._client(ep).put_vars(pairs)
            except (OSError, ConnectionError, RuntimeError):
                raise _MemberDied(ep)
        return set(filled)

    def _count_membership(self, old: ClusterView, ps, tr):
        for kind, before, now in (("pserver", old.pservers, ps),
                                  ("trainer", old.trainers, tr)):
            joined = set(now.items()) - set(before.items())
            left = set(before.items()) - set(now.items())
            if joined:
                _M_MEMBERSHIP.labels(kind=kind, event="join").inc(
                    len(joined))
            if left:
                _M_MEMBERSHIP.labels(kind=kind, event="leave").inc(
                    len(left))

    def _snapshot_dir(self, index: int):
        if callable(self.snapshot_dirs):
            return self.snapshot_dirs(index)
        return self.snapshot_dirs.get(index)

    def _consolidate(self, placement: Dict[str, str], eps,
                     src: ClusterView, needed: set) -> set:
        """Probe every live member (HAVE) and repair placement reality:
        stray copies move onto their placed owners, previously-placed
        vars held NOWHERE (an interrupted earlier transition) join
        `needed` for trainer-held recovery, and never-placed vars held
        nowhere are left alone (zeroing them could mask a pserver whose
        startup has not run yet).  Runs fenced, like _migrate.  Returns
        the names CONFIRMED present on their placed owner — the drop
        phase's license to erase copies elsewhere."""
        all_names = sorted(placement)
        held: Dict[str, set] = {}
        for ep in eps:
            try:
                held[ep] = self._client(ep).have_vars(all_names)
            except (OSError, ConnectionError, RuntimeError):
                raise _MemberDied(ep)
        moves: Dict[str, Dict[str, list]] = {}  # src_ep -> owner -> names
        owner_ok: set = set()
        missing = []
        for name in all_names:
            owner = placement[name]
            if name in held[owner]:
                # a copy is where it belongs.  It stays in `needed`
                # though: the held copy may be a stale bootstrap value
                # and a subscribed trainer's push is fresher — but the
                # zero-fill fallback skips owner_ok names, so an
                # un-pushed copy survives instead of being zeroed
                owner_ok.add(name)
                continue
            src_ep = next((ep for ep in eps if name in held[ep]), None)
            if src_ep is None:
                missing.append(name)
                continue
            moves.setdefault(src_ep, {}).setdefault(owner,
                                                    []).append(name)
        moved_bytes, moved_vars = 0, 0
        for src_ep, by_dst in moves.items():
            for owner, batch in by_dst.items():
                try:
                    vals = self._client(src_ep).get_vars(batch)
                except (OSError, ConnectionError, RuntimeError):
                    raise _MemberDied(src_ep)
                try:
                    moved_bytes += self._client(owner).put_vars(
                        list(zip(batch, vals)))
                except (OSError, ConnectionError, RuntimeError):
                    raise _MemberDied(owner)
                moved_vars += len(batch)
                owner_ok.update(batch)
        if moved_vars:
            _M_MIGRATION_BYTES.inc(moved_bytes)
            _LOG.info(
                "consolidation: moved %d stray vars (%d bytes) onto "
                "their placed owners", moved_vars, moved_bytes)
        homeless = []
        for name in missing:
            if name in src.placement:
                needed.add(name)  # placed once, lost since: recover
            elif name not in needed:
                homeless.append(name)
        if homeless:
            _LOG.info(
                "bootstrap: no live member holds %s yet — reads fail "
                "until some member or trainer installs them",
                sorted(homeless))
        return owner_ok

    def _migrate(self, old: ClusterView, placement: Dict[str, str],
                 live: set) -> List[str]:
        """Move shards to their new owners.  Returns names with no
        recoverable source (dead owner, no snapshot) for the
        trainer-held recovery phase."""
        moves: Dict[str, Dict[str, list]] = {}  # old_ep -> new_ep -> names
        lost: Dict[str, list] = {}              # dead old_ep -> names
        for name, new_ep in placement.items():
            old_ep = old.placement.get(name)
            if old_ep is None or old_ep == new_ep:
                continue  # initial placement or unchanged owner
            if old_ep in live:
                moves.setdefault(old_ep, {}).setdefault(new_ep,
                                                        []).append(name)
            else:
                lost.setdefault(old_ep, []).append(name)
        needed: List[str] = []
        moved_bytes = 0
        for old_ep, by_dst in moves.items():
            fault_injector().fire("cluster.migrate")
            for new_ep, names in by_dst.items():
                try:
                    vals = self._client(old_ep).get_vars(names)
                except (OSError, ConnectionError):
                    raise _MemberDied(old_ep)
                except RuntimeError:
                    # the source is alive but CANNOT serve (ERR reply:
                    # e.g. it restarted blank since the last view) —
                    # recover these names like a dead member's instead
                    # of evicting a healthy server
                    lost.setdefault(old_ep, []).extend(names)
                    continue
                try:
                    moved_bytes += self._client(new_ep).put_vars(
                        list(zip(names, vals)))
                except (OSError, ConnectionError, RuntimeError):
                    raise _MemberDied(new_ep)
        # dead members: latest shard snapshot, else trainer-held copy
        old_index = {ep: i for i, ep in old.pservers.items()}
        for old_ep, names in lost.items():
            fault_injector().fire("cluster.migrate")
            data = None
            snap_dir = self._snapshot_dir(old_index.get(old_ep, -1))
            if snap_dir:
                from ..parallel.checkpoint import latest_pserver_shard

                data, rnd, _ = latest_pserver_shard(snap_dir)
                if data is not None:
                    _LOG.info(
                        "rebalance: restoring %d vars of dead pserver "
                        "%s from its round-%d snapshot", len(names),
                        old_ep, rnd)
            by_dst: Dict[str, list] = {}
            for name in names:
                if data is not None and name in data:
                    by_dst.setdefault(placement[name], []).append(
                        (name, data[name]))
                else:
                    needed.append(name)
            for new_ep, pairs in by_dst.items():
                try:
                    moved_bytes += self._client(new_ep).put_vars(pairs)
                except (OSError, ConnectionError, RuntimeError):
                    raise _MemberDied(new_ep)
        if moved_bytes:
            _M_MIGRATION_BYTES.inc(moved_bytes)
        return needed

    # -- view protocol server ----------------------------------------------
    # line-oriented, compact-JSON answers (RegistryClient idiom):
    #   VIEW\n                         -> OK <view json>\n
    #   WAIT <min_epoch> <timeout_ms>\n-> OK <view json>\n | TIMEOUT\n
    #   DEFINE <json var descs>\n      -> OK\n
    #   PUSHED <epoch> <json names>\n  -> OK\n
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # NOT retained in self._threads: every ClusterClient
            # roundtrip is one short-lived connection, so keeping a
            # Thread object per accept would grow without bound over a
            # long run; these are daemons that exit with their socket
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket):
        try:
            f = conn.makefile("rw", newline="\n")
            while not self._stop.is_set():
                line = f.readline()
                if not line:
                    return
                try:
                    reply = self._handle_line(line.strip())
                except Exception as e:
                    reply = f"ERR {type(e).__name__}: {e}"
                f.write(reply + "\n")
                f.flush()
        except (OSError, ValueError):
            pass
        finally:
            conn.close()

    def _handle_line(self, line: str) -> str:
        if not line:
            return "ERR empty request"
        cmd, _, rest = line.partition(" ")
        if cmd == "VIEW":
            return "OK " + self.view().to_json()
        if cmd == "WAIT":
            min_epoch, timeout_ms = rest.split()
            got = self.wait_view(int(min_epoch),
                                 timeout_s=int(timeout_ms) / 1000.0)
            return "OK " + got.to_json() if got is not None else "TIMEOUT"
        if cmd == "DEFINE":
            from ..parallel.distributed_spliter import VarDesc

            descs = [VarDesc(d["name"], tuple(d.get("shape") or ()),
                             d.get("dtype", "float32"))
                     for d in json.loads(rest)]
            self.define(descs)
            return "OK"
        if cmd == "PUSHED":
            epoch, _, names_json = rest.partition(" ")
            names = set(json.loads(names_json))
            with self._lock:
                if self._view.epoch == int(epoch):
                    self._needed -= names
                    self._lock.notify_all()
            return "OK"
        return f"ERR unknown command {cmd!r}"


class ClusterClient:
    """Subscriber surface over a remote (or in-process) controller.

    Trainers hand an instance to ``parallel.comm.set_cluster`` (or set
    ``PADDLE_TPU_CONTROLLER`` and let the comm layer build one); the
    fused send op then derives endpoint maps from the current view and
    retries failed rounds against fresh views.  ``set_param_provider``
    arms trainer-held recovery: during a rebalance that lost shards
    with no snapshot, the client pushes the provider's copies straight
    to the new owner pservers over PUT_BATCH."""

    def __init__(self, controller, timeout_s: float = 10.0,
                 poll_s: float = 0.5,
                 retry_policy: Optional[RetryPolicy] = None):
        # `controller` is an address string or an in-process
        # ClusterController (tests / single-process clusters)
        self._ctl = controller if not isinstance(controller, str) else None
        self._addr = None
        if isinstance(controller, str):
            host, port = controller.rsplit(":", 1)
            self._addr = (host, int(port))
        self._timeout = timeout_s
        self.poll_s = float(poll_s)
        self.policy = retry_policy or RetryPolicy.from_env(
            "CLUSTER_RETRY", max_attempts=3, base_delay=0.05,
            max_delay=0.5, deadline=5.0)
        self._provider: Optional[Callable[[str], object]] = None
        self._pushed: set = set()  # (epoch, name) already pushed
        self._cached: Optional[ClusterView] = None
        self._cached_at = 0.0
        self._lease: Optional[Lease] = None

    # -- wire ---------------------------------------------------------------
    def _roundtrip(self, line: str, timeout_s: Optional[float] = None) \
            -> str:
        if self._ctl is not None:
            return self._ctl._handle_line(line)

        def once():
            with socket.create_connection(
                    self._addr,
                    timeout=timeout_s or self._timeout) as s:
                s.sendall(line.encode() + b"\n")
                reply = s.makefile("r").readline()
                if not reply:
                    raise OSError("controller closed connection")
                return reply.strip()

        return self.policy.call(once, what=(
            f"cluster controller at "
            f"{self._addr[0]}:{self._addr[1]} unreachable"))

    @staticmethod
    def _parse(reply: str) -> ClusterView:
        if not reply.startswith("OK "):
            raise RuntimeError(f"cluster controller error: {reply}")
        return ClusterView.from_json(reply[3:])

    # -- views --------------------------------------------------------------
    def view(self) -> ClusterView:
        v = self._parse(self._roundtrip("VIEW"))
        self._cached, self._cached_at = v, time.monotonic()
        return v

    def wait_view(self, min_epoch: int,
                  timeout_s: float = 30.0) -> Optional[ClusterView]:
        """Next stable view with epoch >= min_epoch, or None."""
        deadline = time.monotonic() + timeout_s
        try:
            # a rebalance may ALREADY be waiting on our shard pushes —
            # check before blocking in WAIT, so trainer-held recovery
            # is prompt instead of deferred to the first WAIT timeout
            v = self.view()
            if v.status == "rebalancing":
                self._maybe_push_needed(v)
        except OSError:
            pass  # the WAIT loop below retries through the policy
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                return None
            # bounded server-side waits so a controller restart turns
            # into a retried request instead of a stuck socket; poll
            # fast when a rebalance is waiting on OUR shard pushes
            v = self._cached
            chunk = min(left, 5.0)
            if (self._provider is not None and v is not None
                    and v.status == "rebalancing" and v.needed):
                chunk = min(left, 0.5)
            reply = self._roundtrip(
                f"WAIT {int(min_epoch)} {int(chunk * 1000)}",
                timeout_s=chunk + self._timeout)
            if reply == "TIMEOUT":
                # refresh FIRST: a rebalance that started mid-WAIT is
                # only visible in a fresh view, and its `needed` list
                # is what trainer-held recovery pushes against
                v = self.view()
                if v.status == "rebalancing":
                    self._maybe_push_needed(v)
                continue
            v = self._parse(reply)
            self._cached, self._cached_at = v, time.monotonic()
            return v

    def ready_view(self, timeout_s: float = 60.0) -> ClusterView:
        """The current STABLE view with a placement, waiting out (and
        participating in) any rebalance in progress."""
        v = self._cached
        if (v is not None and v.status == "stable" and v.placement
                and time.monotonic() - self._cached_at < self.poll_s):
            return v
        deadline = time.monotonic() + timeout_s
        while True:
            v = self.view()
            if v.status == "stable" and v.placement:
                return v
            if v.status == "rebalancing" and v.needed:
                self._maybe_push_needed(v)
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"cluster: no stable view within {timeout_s}s "
                    f"(last: {v!r})")
            time.sleep(min(self.poll_s, 0.1))

    # -- membership ---------------------------------------------------------
    def join(self, kind: str, addr: Optional[str] = None,
             ttl_s: float = 2.0, on_lost=None) -> Lease:
        """Register this process as a cluster member (a trainer lease
        is what lets the controller adapt fan-in and the master reclaim
        task chunks when this process dies)."""
        reg_addr = self.view().registry
        if not reg_addr:
            raise RuntimeError("cluster view carries no registry address")
        addr = addr or f"{socket.gethostname()}:{os.getpid()}"
        self._lease = Lease(RegistryClient(reg_addr), kind, addr,
                            ttl_s=ttl_s, on_lost=on_lost)
        return self._lease

    def leave(self):
        if self._lease is not None:
            self._lease.release()
            self._lease = None

    # -- trainer-held shard recovery ----------------------------------------
    def set_param_provider(self, provider: Callable[[str], object]):
        """``provider(name) -> value or None``: the local parameter
        copies this process can contribute during a rebalance whose
        shards have no other source (typically the trainer scope —
        params there are refreshed by every round's pull)."""
        self._provider = provider

    def _maybe_push_needed(self, view: ClusterView):
        if self._provider is None or not view.needed:
            return
        # older epochs can never be pushed again — prune them so a
        # long-running job with periodic churn cannot grow this set
        # without bound
        self._pushed = {k for k in self._pushed if k[0] >= view.epoch}
        by_dst: Dict[str, list] = {}
        pushed = []
        for name in view.needed:
            key = (view.epoch, name)
            ep = view.placement.get(name)
            if key in self._pushed or ep is None:
                continue
            try:
                val = self._provider(name)
            except Exception:
                val = None
            if val is None:
                continue
            by_dst.setdefault(ep, []).append((name, val))
            pushed.append(name)
            self._pushed.add(key)
        if not by_dst:
            return
        from ..parallel.pserver import VariableClient

        for ep, pairs in by_dst.items():
            try:
                # a DEDICATED short-lived client, NOT the comm pool's:
                # pooled client sockets are only safe on their
                # endpoint's worker thread, and this runs on whatever
                # thread polled the view — possibly concurrent with a
                # round in flight on the same endpoint
                c = VariableClient(
                    ep, connect_timeout=2.0, request_timeout=15.0,
                    retry_policy=RetryPolicy.from_env(
                        "ELASTIC_RETRY", max_attempts=2,
                        base_delay=0.05, max_delay=0.25, deadline=2.0))
                try:
                    c.put_vars(pairs)
                finally:
                    c.close()
            except Exception as e:
                # a push is RECOVERY ASSIST: any failure (dead socket,
                # ERR reply like "batch too large") must not crash the
                # healthy trainer it runs on — un-mark so another
                # subscriber (or a later poll) can try
                _LOG.warning("trainer-held push to %s failed: %s", ep, e)
                for name, _ in pairs:
                    self._pushed.discard((view.epoch, name))
                    pushed.remove(name)
        if pushed:
            _LOG.info("pushed trainer-held copies of %s for view %d",
                      pushed, view.epoch)
            try:
                self._roundtrip(
                    f"PUSHED {view.epoch} {json.dumps(sorted(pushed))}")
            except OSError as e:
                # the values landed; a lost ack at worst lets the
                # controller fall back to its zero-fill degrade path —
                # strictly better than killing this trainer over it
                _LOG.warning("PUSHED ack for view %d failed: %s",
                             view.epoch, e)

    # -- var definitions ----------------------------------------------------
    def define(self, var_descs: Sequence):
        payload = json.dumps([
            {"name": v.name, "shape": list(v.shape or ()),
             "dtype": str(v.dtype)} for v in var_descs])
        reply = self._roundtrip("DEFINE " + payload)
        if not reply.startswith("OK"):
            raise RuntimeError(f"cluster controller error: {reply}")

    def close(self):
        self.leave()
