"""Task-dispatch master: Python surface over the native implementation.

Reference: /root/reference/go/master/service.go (task queues, timeout
re-dispatch, failureMax discard, snapshot/recover) and
python/paddle/v2/master/client.py:29-117 (the trainer-side client:
set_dataset / next record paradigm).
"""
from __future__ import annotations

import ctypes
import socket
import time
from typing import List, Optional, Sequence

from paddle_tpu import native
from paddle_tpu.core.resilience import RetryPolicy, fault_injector
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import tracing as obs_tracing

# task-dispatch telemetry (gated by PADDLE_TPU_METRICS).  The master
# protocol itself is owned by the native server, so trace context is not
# carried on this wire; instead each client roundtrip gets a span and
# the whole chunk-processing window of a task records as `master.task` —
# reader work done while a task is held nests under it.
_M_REQUESTS = obs_metrics.counter(
    "paddle_tpu_master_requests_total",
    "master-client roundtrips, by verb", ("verb",))
_M_TASKS = obs_metrics.counter(
    "paddle_tpu_master_tasks_total",
    "task lifecycle acks sent to the master", ("result",))


def _declare(l):
    if getattr(l, "_master_declared", False):
        return l
    p, sz, i = ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int
    i64 = ctypes.c_int64
    l.pt_master_create.restype = p
    l.pt_master_create.argtypes = [i, ctypes.c_double, ctypes.c_char_p]
    l.pt_master_set_dataset.restype = i
    l.pt_master_set_dataset.argtypes = [
        p, ctypes.POINTER(ctypes.c_char_p), sz, sz,
    ]
    l.pt_master_has_dataset.restype = i
    l.pt_master_has_dataset.argtypes = [p]
    l.pt_master_get_task.restype = i
    l.pt_master_get_task.argtypes = [
        p, ctypes.POINTER(i64), ctypes.c_char_p, sz,
    ]
    l.pt_master_task_finished.restype = i
    l.pt_master_task_finished.argtypes = [p, i64]
    l.pt_master_task_failed.restype = i
    l.pt_master_task_failed.argtypes = [p, i64]
    l.pt_master_counts.argtypes = [p, ctypes.POINTER(i64)]
    l.pt_master_serve.restype = i
    l.pt_master_serve.argtypes = [p, i]
    l.pt_master_stop.argtypes = [p]
    l.pt_master_destroy.argtypes = [p]
    l._master_declared = True
    return l


class Master:
    """In-process master; optionally served over TCP for remote trainers.

    failure_max / timeout_s mirror the reference's task re-dispatch policy
    (service.go checkTimeoutFunc/processFailedTask); snapshot_path enables
    crash recovery (service.go snapshot/recover — a file here, etcd there).
    """

    def __init__(self, failure_max: int = 3, timeout_s: float = 60.0,
                 snapshot_path: Optional[str] = None):
        self._l = _declare(native.lib())
        self._h = self._l.pt_master_create(
            failure_max, timeout_s,
            snapshot_path.encode() if snapshot_path else None,
        )
        self.port = None

    def set_dataset(self, chunks: Sequence[str], chunks_per_task: int = 1):
        arr = (ctypes.c_char_p * len(chunks))(
            *[c.encode() for c in chunks]
        )
        self._l.pt_master_set_dataset(
            self._h, arr, len(chunks), chunks_per_task
        )

    @property
    def has_dataset(self) -> bool:
        return bool(self._l.pt_master_has_dataset(self._h))

    def get_task(self):
        """-> (task_id, [chunks]) or None if nothing available right now."""
        tid = ctypes.c_int64()
        buf = ctypes.create_string_buffer(1 << 20)
        st = self._l.pt_master_get_task(
            self._h, ctypes.byref(tid), buf, len(buf)
        )
        if st == 0:
            return None
        chunks = buf.value.decode().split("\n") if buf.value else []
        return tid.value, chunks

    def task_finished(self, task_id: int) -> bool:
        return bool(self._l.pt_master_task_finished(self._h, task_id))

    def task_failed(self, task_id: int) -> bool:
        return bool(self._l.pt_master_task_failed(self._h, task_id))

    def counts(self) -> dict:
        out = (ctypes.c_int64 * 5)()
        self._l.pt_master_counts(self._h, out)
        return {
            "todo": out[0], "pending": out[1], "done": out[2],
            "discarded": out[3], "pass": out[4],
        }

    # same surface as MasterClient so readers work against either
    info = counts

    def reclaim_expired(self) -> dict:
        """Run the lazy task-timeout check NOW and return the
        post-reclaim counts.

        The native master reclaims expired leases inside get_task /
        counts (service.go checkTimeoutFunc is a ticker there; here the
        check is amortized onto trainer roundtrips).  That is correct
        but LAZY: a task leased to a SIGKILLed trainer re-dispatches
        only when some surviving trainer next polls.  The elastic
        ClusterController pokes this on every trainer-lease expiry so
        orphaned chunks requeue as soon as ``timeout_s`` allows.

        Reclamation is exactly-once per expiry: the timeout sweep moves
        the task out of `pending` under the master lock, so a second
        sweep (or the vanished trainer's late FIN/FAIL ack) finds
        nothing — the late ack is rejected as stale and does NOT bump
        the task's `failure_max` accounting a second time
        (tests/test_elastic.py pins this)."""
        return self.counts()

    def serve(self, port: int = 0) -> int:
        """Start the TCP server; returns the bound port."""
        self.port = self._l.pt_master_serve(self._h, port)
        if self.port < 0:
            raise OSError("master: failed to bind server socket")
        return self.port

    def stop(self):
        self._l.pt_master_stop(self._h)

    def __del__(self):
        # interpreter shutdown may have torn down ctypes/native state in
        # any order; destroying twice or raising from __del__ would turn
        # a clean exit into "Exception ignored in" noise
        try:
            h = getattr(self, "_h", None)
            if h:
                self._h = None
                self._l.pt_master_destroy(h)
        except Exception:
            pass


class MasterClient:
    """TCP client for a remote Master (the cgo client.py analogue).

    Reconnects on socket failure — a trainer may outlive a restarted master
    (whose state comes back from its snapshot)."""

    def __init__(self, addr: str, retry_interval: float = 0.2,
                 timeout: float = 30.0,
                 retry_policy: Optional[RetryPolicy] = None):
        self.host, port = addr.rsplit(":", 1)
        self.port = int(port)
        self.retry_interval = retry_interval
        self.timeout = timeout
        # legacy kwargs map onto the policy: retry_interval seeds the
        # backoff, timeout bounds the whole retry sequence (the old flat
        # 50 x retry_interval loop is the from_env default's ancestor)
        self.policy = retry_policy or RetryPolicy.from_env(
            "MASTER_RETRY", max_attempts=50, base_delay=retry_interval,
            max_delay=max(retry_interval, 2.0), deadline=timeout)
        self._sock = None
        self._f = None

    def _connect(self):
        if self._sock is not None:
            return
        fault_injector().fire("master.connect")
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self._f = self._sock.makefile("rw", newline="\n")

    def _reset(self):
        # close the buffered file FIRST (with its flush suppressed):
        # closing only the socket leaves _f to flush buffered bytes at GC
        # time, which raises into "Exception ignored" noise during
        # interpreter shutdown when the server died mid-roundtrip
        f, s = self._f, self._sock
        self._sock = self._f = None
        for obj in (f, s):
            try:
                if obj is not None:
                    obj.close()
            except (OSError, ValueError):
                pass

    def _roundtrip(self, req: str, read_payload=False):
        verb = req.split(None, 1)[0] if req.strip() else "?"
        _M_REQUESTS.labels(verb=verb).inc()
        with obs_tracing.span("master.client." + verb.lower(),
                              endpoint=f"{self.host}:{self.port}"):
            return self._roundtrip_attempts(req, read_payload)

    def _roundtrip_attempts(self, req: str, read_payload=False):
        state = self.policy.begin()
        while True:
            try:
                self._connect()
                raw = req.encode()
                data = fault_injector().mangle("master.send", raw)
                if data != raw:
                    # injected mid-write crash / wire corruption: ship
                    # the mangled frame so the server sees it, then fail
                    # our side like the sender died
                    self._sock.sendall(data)
                    raise OSError("fault injection: mangled frame")
                self._f.write(req)
                self._f.flush()
                line = self._f.readline()
                if not line:
                    raise OSError("master connection closed")
                payload = None
                if read_payload and line.startswith("OK"):
                    payload = []
                    while True:
                        ln = self._f.readline()
                        if not ln:
                            raise OSError("master connection closed")
                        if ln.rstrip("\n") == ".":
                            break
                        payload.append(ln.rstrip("\n"))
                return line.rstrip("\n"), payload
            except OSError as e:
                self._reset()
                state.record(e, what=(f"master at {self.host}:{self.port} "
                                      "unreachable"))
                state.sleep()

    def set_dataset(self, chunks: Sequence[str], chunks_per_task: int = 1):
        req = f"SET {chunks_per_task} {len(chunks)}\n" + "".join(
            c + "\n" for c in chunks
        )
        line, _ = self._roundtrip(req)
        return line == "OK"

    def get_task(self):
        line, payload = self._roundtrip("GET\n", read_payload=True)
        if line == "NONE":
            return None
        _, _st, tid = line.split()
        return int(tid), payload

    def task_finished(self, task_id: int) -> bool:
        ok = self._roundtrip(f"FIN {task_id}\n")[0] == "OK"
        if ok:  # a rejected stale ack must not count as a completion
            _M_TASKS.labels(result="finished").inc()
        return ok

    def task_failed(self, task_id: int) -> bool:
        ok = self._roundtrip(f"FAIL {task_id}\n")[0] == "OK"
        if ok:
            _M_TASKS.labels(result="failed").inc()
        return ok

    def info(self) -> dict:
        line, _ = self._roundtrip("INFO\n")
        parts = line.split()
        return dict(
            zip(
                ("todo", "pending", "done", "discarded", "pass"),
                map(int, parts[1:]),
            )
        )

    # INFO runs the server's lazy timeout sweep, so poking a REMOTE
    # master is the same roundtrip (Master.reclaim_expired docs)
    reclaim_expired = info

    def close(self):
        self._reset()

    def __del__(self):
        try:
            self._reset()
        except Exception:
            pass


def task_record_reader(client, chunk_reader, poll_interval: float = 0.05,
                       stop_after_pass: bool = True,
                       on_chunk_error: str = "raise"):
    """Elastic reader: pull tasks from the master, yield records from each
    chunk via `chunk_reader(chunk) -> iterable`, ack on success, nack on
    error (reference v2/reader/creator.py:60-117 cloud_reader +
    master client NextRecord).

    One call iterates one dataset pass: it stops when the master rolls over
    to a new pass (status 2 on a later get_task) — so a fresh call starts
    the next pass, matching the epoch-per-call reader convention.

    `on_chunk_error` decides what happens after a failing chunk_reader is
    nacked (`task_failed`, so the master re-dispatches the task and
    discards it after failure_max nacks — service.go processFailedTask):
    "raise" propagates and kills this reader (a second reader picks the
    task up); "skip" moves on to the next task, so one surviving reader
    can drive a poisoned task to discard and still finish the pass.
    """
    if on_chunk_error not in ("raise", "skip"):
        raise ValueError(f"on_chunk_error={on_chunk_error!r}: "
                         "expected 'raise' or 'skip'")

    def reader():
        while True:
            got = client.get_task()
            if got is None:
                info = client.info()
                if info["todo"] == 0 and info["pending"] == 0:
                    return  # nothing left this pass
                time.sleep(poll_interval)  # others hold pending tasks
                continue
            tid, chunks = got
            # the task's processing window spans many yields, so a
            # context-managed span would stay pushed on the consumer's
            # stack between resumes (and forever, if the reader is
            # abandoned) — record it detached at the end instead
            task_parent = obs_tracing.current_context()
            t_wall, t0 = time.time(), time.perf_counter()

            def _record_task(ok):
                obs_tracing.record_span(
                    "master.task", t_wall, time.perf_counter() - t0,
                    parent=task_parent, task_id=tid,
                    chunks=len(chunks), ok=ok)

            try:
                for chunk in chunks:
                    yield from chunk_reader(chunk)
            except Exception:
                _record_task(False)
                client.task_failed(tid)
                if on_chunk_error == "raise":
                    raise
                # a nack that DISCARDED the task may have drained the
                # pass (todo and pending both empty); without this check
                # the next get_task would roll into a new pass and this
                # reader would re-yield chunks it already served
                if stop_after_pass:
                    info = client.info()
                    if info["todo"] == 0 and info["pending"] == 0:
                        return
                continue
            _record_task(True)
            client.task_finished(tid)
            if stop_after_pass:
                info = client.info()
                if info["todo"] == 0 and info["pending"] == 0:
                    return

    return reader
