"""Cloud/elastic training services: task-dispatch master + elastic readers.

The TPU rebuild of the reference's Go cloud layer (/root/reference/go/):
fault-tolerant dataset dispatch (go/master/service.go) with stateless,
elastic trainers (doc/design/cluster_train/README.md).  The master itself is
native C++ (paddle_tpu/native/src/master.cc); this package provides the
Python client surface that python/paddle/v2/master/client.py provided over
cgo there.
"""
from .master import Master, MasterClient, task_record_reader

__all__ = ["Master", "MasterClient", "task_record_reader",
           "ReplicaRouter", "NoReplicasAvailable",
           "Autoscaler", "AutoscalerPolicy",
           "SubprocessReplicaLauncher"]


def __getattr__(name):
    # the serving front door (cloud/router.py, cloud/autoscaler.py)
    # pulls in the whole serving subsystem; load it on first use so
    # cloud-only users (masters, pservers, cluster controllers) stay
    # light
    if name in ("ReplicaRouter", "NoReplicasAvailable"):
        from . import router

        return getattr(router, name)
    if name in ("Autoscaler", "AutoscalerPolicy",
                "SubprocessReplicaLauncher"):
        from . import autoscaler

        return getattr(autoscaler, name)
    raise AttributeError(name)
