"""Signal-driven autoscaler for the serving fleet: the elastic runtime
meets the router (ROADMAP item 4).

PR 7 gave membership a TTL-lease registry, PR 8 gave serving a
`ReplicaRouter` front door, PR 13 gave the router windowed
p99/qps/backlog series (`router.signals()`).  This module closes the
loop: a controller that watches those signals and grows or shrinks the
`cli serve` replica fleet itself —

* **scale-out** — sustained backlog (reserved-token queue) or p99 burn
  above target spawns one replica against the router's lease registry.
  The cold-start enabler is the WARM-START artifact
  (serving.save_generation_model(warm_start=True)): the new process
  points PADDLE_TPU_COMPILATION_CACHE_DIR at the model dir's
  ``xla_cache`` and deserializes its executables instead of compiling,
  so time-to-first-token is bounded by model load, not XLA;
* **scale-in** — sustained idle retires one replica via graceful
  drain: mark it draining at the router (no new placements), send the
  replica `drain` verb (stop admission, finish every accepted stream —
  the PR 8 one-at-a-time swap machinery), then release it (SIGTERM for
  replicas this process spawned — `cli serve` exits gracefully,
  releasing its lease first — or the wire `stop` op for adopted ones);
* **robustness is the headline, not the policy**:
  - hysteresis + sustain windows + cooldown: a noisy signal that
    oscillates across a threshold keeps resetting the sustain clock
    and can never flap the fleet (test-pinned);
  - a min/max replica band the fleet can never leave;
  - the at-least-one-replica invariant holds even when scale-in races
    a SIGKILL: survivors are re-counted AFTER the victim drained, and
    if the fleet shrank in the meantime the victim is resumed instead
    of retired;
  - a crash-looping replica (spawned process dies before it ever
    serves, `crash_loop_limit` times in a row) trips exponential
    backoff and the ``paddle_tpu_autoscaler_crashloops_total`` alert
    counter (tools/slo.json gates it);
  - chaos sites ``autoscaler.spawn`` / ``autoscaler.drain`` run
    through the PR 1 FaultInjector: an injected error aborts that
    action cleanly (resumed victim, counted spawn failure), never the
    control loop.

Surfaces: embed ``Autoscaler(router, launcher)`` next to your
ReplicaRouter, or run ``python -m paddle_tpu.cli autoscale MODEL_DIR``
as the operator front door.  docs/serving.md "Autoscaling" has the
runbook and knob table.
"""
from __future__ import annotations

import itertools
import logging
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from paddle_tpu.core.resilience import fault_injector
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.serving.replica import replica_call

__all__ = ["AutoscalerPolicy", "Autoscaler",
           "SubprocessReplicaLauncher", "ReplicaProcess"]

_LOG = logging.getLogger("paddle_tpu.autoscaler")

_SCALER_IDS = itertools.count()
_M_LIVE = obs_metrics.gauge(
    "paddle_tpu_autoscaler_replicas_live",
    "serving replicas live and routable (draining excluded)",
    ("scaler",), always=True)
_M_DESIRED = obs_metrics.gauge(
    "paddle_tpu_autoscaler_replicas_desired",
    "replica count the autoscaler is currently steering toward",
    ("scaler",), always=True)
_M_EVENTS = obs_metrics.counter(
    "paddle_tpu_autoscaler_scale_events_total",
    "completed scale actions by direction (out/in)",
    ("scaler", "direction"), always=True)
_M_ABORTS = obs_metrics.counter(
    "paddle_tpu_autoscaler_scale_aborts_total",
    "scale actions aborted mid-flight (invariant re-check, injected "
    "fault, victim death)", ("scaler",), always=True)
_M_CRASHLOOPS = obs_metrics.counter(
    "paddle_tpu_autoscaler_crashloops_total",
    "crash-loop detections: a spawned replica died before first "
    "serving, crash_loop_limit times in a row (backoff armed)",
    ("scaler",), always=True)
_M_SPAWN_FAILS = obs_metrics.counter(
    "paddle_tpu_autoscaler_spawn_failures_total",
    "replica spawns that never became live", ("scaler",), always=True)
_M_SPAWN_S = obs_metrics.histogram(
    "paddle_tpu_autoscaler_spawn_seconds",
    "spawn -> live-in-the-routing-table latency (the cold-start cost "
    "the warm-start artifact bounds)", ("scaler",), always=True)


# ---------------------------------------------------------------------------
# policy: pure decision logic (unit-testable with synthetic signals)
# ---------------------------------------------------------------------------


def _num(v, default=None):
    """None/NaN-tolerant float: windowed quantiles are NaN before
    traffic and gauges are None before their first sample."""
    if v is None:
        return default
    try:
        f = float(v)
    except (TypeError, ValueError):
        return default
    if f != f:  # NaN
        return default
    return f


class AutoscalerPolicy:
    """Hysteresis + sustain + cooldown over the router's windowed
    signals.  `observe(signals, live, now)` returns +1 (scale out),
    -1 (scale in) or 0; the caller reports back with
    `record_action(now)` when an action COMPLETES so the cooldown
    window starts from completion, not decision.

    Three signal zones make the hysteresis explicit:

    * HOT    — backlog > `backlog_high` or p99 > `p99_high_s`;
    * COLD   — backlog <= `backlog_low` and p99 <= `p99_low_s` (or no
               latency data at all: an idle fleet has no p99);
    * middle — the hysteresis band: both sustain clocks RESET, so a
      signal oscillating across either threshold can never accumulate
      the sustain a scale action requires (no flapping, test-pinned).

    HOT must hold continuously for `sustain_s` to scale out; COLD for
    `idle_sustain_s` (deliberately longer: growing late queues
    requests, shrinking early thrashes) to scale in; and any action
    starts a `cooldown_s` refractory window."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4, *,
                 p99_high_s: float = 2.0,
                 p99_low_s: Optional[float] = None,
                 backlog_high: float = 512.0,
                 backlog_low: float = 32.0,
                 sustain_s: float = 3.0,
                 idle_sustain_s: float = 10.0,
                 cooldown_s: float = 15.0):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1 (the fleet "
                             "never scales to zero)")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas < min_replicas")
        if backlog_low >= backlog_high:
            raise ValueError(
                "hysteresis needs backlog_low < backlog_high "
                f"(got {backlog_low} >= {backlog_high})")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.p99_high_s = float(p99_high_s)
        self.p99_low_s = (float(p99_low_s) if p99_low_s is not None
                          else float(p99_high_s) / 4.0)
        if self.p99_low_s > self.p99_high_s:
            raise ValueError("p99_low_s > p99_high_s")
        self.backlog_high = float(backlog_high)
        self.backlog_low = float(backlog_low)
        self.sustain_s = float(sustain_s)
        self.idle_sustain_s = float(idle_sustain_s)
        self.cooldown_s = float(cooldown_s)
        self._hot_since: Optional[float] = None
        self._cold_since: Optional[float] = None
        self._cooldown_until = float("-inf")
        self.last_reason = "no signal yet"

    # -- zone classification ------------------------------------------------
    def is_hot(self, signals: Dict) -> bool:
        backlog = _num(signals.get("outstanding_tokens"), 0.0)
        p99 = _num(signals.get("p99"))
        return (backlog > self.backlog_high
                or (p99 is not None and p99 > self.p99_high_s))

    def is_cold(self, signals: Dict) -> bool:
        backlog = _num(signals.get("outstanding_tokens"), 0.0)
        p99 = _num(signals.get("p99"))
        return (backlog <= self.backlog_low
                and (p99 is None or p99 <= self.p99_low_s))

    # -- the decision -------------------------------------------------------
    def observe(self, signals: Dict, live: int, now: float) -> int:
        hot, cold = self.is_hot(signals), self.is_cold(signals)
        if hot:
            self._cold_since = None
            if self._hot_since is None:
                self._hot_since = now
        elif cold:
            self._hot_since = None
            if self._cold_since is None:
                self._cold_since = now
        else:
            # the hysteresis band: reset BOTH clocks — this is what
            # pins a noisy signal to zero scale events
            self._hot_since = None
            self._cold_since = None
            self.last_reason = "in hysteresis band"
            return 0
        if now < self._cooldown_until:
            self.last_reason = (f"cooldown "
                                f"({self._cooldown_until - now:.1f}s "
                                "left)")
            return 0
        if hot and now - self._hot_since >= self.sustain_s:
            if live >= self.max_replicas:
                self.last_reason = (f"hot but at max_replicas="
                                    f"{self.max_replicas}")
                return 0
            self.last_reason = (
                f"hot for {now - self._hot_since:.1f}s (backlog "
                f"{_num(signals.get('outstanding_tokens'), 0.0):.0f}"
                f" / p99 {_num(signals.get('p99'), float('nan')):.3g})")
            return +1
        if cold and now - self._cold_since >= self.idle_sustain_s:
            if live <= self.min_replicas:
                self.last_reason = (f"cold but at min_replicas="
                                    f"{self.min_replicas}")
                return 0
            self.last_reason = (
                f"cold for {now - self._cold_since:.1f}s")
            return -1
        self.last_reason = ("sustaining "
                            + ("hot" if hot else "cold"))
        return 0

    def record_action(self, now: float) -> None:
        """An action COMPLETED: arm the cooldown and reset the sustain
        clocks (the fleet changed, old evidence is stale)."""
        self._hot_since = None
        self._cold_since = None
        self._cooldown_until = now + self.cooldown_s


# ---------------------------------------------------------------------------
# replica process handles
# ---------------------------------------------------------------------------


class ReplicaProcess:
    """One spawned `cli serve` process: the Popen handle plus a stdout
    reader that learns the replica's address from its
    "serving <dir> on <addr>" banner.  Fake handles in tests implement
    the same alive()/terminate()/kill()/addr surface."""

    def __init__(self, proc: subprocess.Popen):
        self.proc = proc
        self.pid = proc.pid
        self.addr: Optional[str] = None
        if proc.stdout is not None:
            t = threading.Thread(target=self._read_banner, daemon=True)
            t.start()

    def _read_banner(self):
        try:
            for line in self.proc.stdout:
                # "serving MODEL_DIR on HOST:PORT[, ...]" — split on
                # the LAST " on " so a model dir containing spaces (or
                # even " on ") still yields the address, never a path
                # fragment that would make _check_pending kill a
                # healthy replica at spawn_timeout
                if line.startswith("serving ") and " on " in line:
                    tail = line.rsplit(" on ", 1)[1].split()
                    if tail:
                        self.addr = tail[0].rstrip(",")
                # keep draining so the child never blocks on a full
                # stdout pipe
        except (OSError, ValueError):
            pass

    def alive(self) -> bool:
        return self.proc.poll() is None

    def terminate(self) -> None:
        """SIGTERM: `cli serve` arms the graceful chain (drain ->
        release lease -> delist telemetry -> flight dump -> exit)."""
        if self.alive():
            self.proc.terminate()

    def kill(self) -> None:
        if self.alive():
            self.proc.kill()

    def wait(self, timeout: Optional[float] = None):
        return self.proc.wait(timeout=timeout)


class SubprocessReplicaLauncher:
    """Spawns `python -m paddle_tpu.cli serve MODEL_DIR --registry ...`
    replicas.  The model dir's warm-start artifact (if shipped) is
    picked up by `cli serve` itself — nothing to configure here."""

    def __init__(self, model_dir: str, registry_addr: str, *,
                 use_tpu: int = 1, ttl_s: float = 2.0,
                 drain_grace_s: float = 30.0,
                 extra_args: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 stderr=subprocess.DEVNULL):
        self.model_dir = model_dir
        self.registry_addr = registry_addr
        self.use_tpu = int(use_tpu)
        self.ttl_s = float(ttl_s)
        self.drain_grace_s = float(drain_grace_s)
        self.extra_args = list(extra_args or ())
        self.env = env
        self.stderr = stderr

    def spawn(self) -> ReplicaProcess:
        cmd = [sys.executable, "-m", "paddle_tpu.cli", "serve",
               self.model_dir, "--registry", self.registry_addr,
               "--use_tpu", str(self.use_tpu),
               "--ttl", str(self.ttl_s),
               "--drain_grace", str(self.drain_grace_s)]
        cmd += self.extra_args
        proc = subprocess.Popen(
            cmd, env=self.env, text=True, stdout=subprocess.PIPE,
            stderr=self.stderr)
        return ReplicaProcess(proc)


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------


class Autoscaler:
    """The scaling control loop beside one ReplicaRouter.

    `poll()` runs one evaluation step (what tests drive directly);
    `start()` runs it on a daemon thread every `poll_s`.  Spawns are
    tracked asynchronously (the loop keeps evaluating while a replica
    boots); scale-ins run synchronously inside poll (a drain SHOULD
    pause further decisions).  `ensure_min()` brings a fresh fleet up
    to the policy's floor."""

    def __init__(self, router, launcher, policy: Optional[AutoscalerPolicy] = None,
                 *, poll_s: float = 0.5, window_s: float = 15.0,
                 spawn_timeout_s: float = 300.0,
                 crash_loop_limit: int = 3,
                 crash_backoff_s: float = 30.0,
                 crash_backoff_max_s: float = 600.0,
                 drain_grace_s: float = 30.0):
        self.router = router
        self.launcher = launcher
        self.policy = policy or AutoscalerPolicy()
        self.poll_s = float(poll_s)
        self.window_s = float(window_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.crash_loop_limit = int(crash_loop_limit)
        self.crash_backoff_s = float(crash_backoff_s)
        self.crash_backoff_max_s = float(crash_backoff_max_s)
        self.drain_grace_s = float(drain_grace_s)
        self._pending: List[tuple] = []   # (handle, t0, live_before)
        self._owned: Dict[str, ReplicaProcess] = {}
        self._unplaced: List[ReplicaProcess] = []  # live, addr unknown
        self._crash_streak = 0
        self._crashloops = 0
        self._backoff_until = float("-inf")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.RLock()
        self.last_event = "idle"
        self.events: List[str] = []
        sid = self._sid = str(next(_SCALER_IDS))
        self._m_live = _M_LIVE.labels(scaler=sid)
        self._m_desired = _M_DESIRED.labels(scaler=sid)
        self._m_out = _M_EVENTS.labels(scaler=sid, direction="out")
        self._m_in = _M_EVENTS.labels(scaler=sid, direction="in")
        self._m_aborts = _M_ABORTS.labels(scaler=sid)
        self._m_crashloops = _M_CRASHLOOPS.labels(scaler=sid)
        self._m_spawn_fails = _M_SPAWN_FAILS.labels(scaler=sid)
        self._m_spawn_s = _M_SPAWN_S.labels(scaler=sid)
        # start the router's sampler now so windowed signals exist by
        # the first decision
        self.router.watch()

    # -- bookkeeping --------------------------------------------------------
    def _note(self, what: str) -> None:
        self.last_event = what
        self.events.append(what)
        del self.events[:-200]
        _LOG.info("autoscaler: %s", what)
        try:
            from paddle_tpu.observability import flightrecorder

            flightrecorder.note("autoscaler", what=what)
        except Exception as e:  # the ring must never break scaling
            _LOG.debug("flight note failed: %r", e)

    def _live(self) -> List[str]:
        return self.router.live_replicas(include_draining=False)

    def _adopt_addrs(self) -> None:
        """Map spawned handles to their registry addresses once the
        banner (or membership) reveals them, so scale-in can SIGTERM a
        process it owns instead of using the wire stop."""
        with self._lock:
            for h in list(self._unplaced):
                if h.addr:
                    self._owned[h.addr] = h
                    self._unplaced.remove(h)
                elif not h.alive():
                    self._unplaced.remove(h)
            # reap owned replicas that died under us (SIGKILL chaos):
            # the process entry is collected and the address forgotten
            # so a later scale-in never tries to drain a corpse
            for addr, h in list(self._owned.items()):
                if not h.alive():
                    try:
                        h.wait(timeout=0)
                    except Exception:
                        pass
                    del self._owned[addr]

    def owned_pids(self) -> Dict[str, int]:
        """{addr: pid} of live replicas this autoscaler spawned — what
        a chaos drill SIGKILLs."""
        self._adopt_addrs()
        with self._lock:
            return {a: h.pid for a, h in self._owned.items()
                    if h.alive()}

    # -- the loop -----------------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._run,
                                            daemon=True,
                                            name="paddle-autoscaler")
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.poll_s):
            try:
                self.poll()
            except Exception as e:
                # one bad poll (registry hiccup, replica race) must
                # never kill the control loop
                _LOG.warning("autoscaler poll failed: %r", e)

    def poll(self, now: Optional[float] = None) -> int:
        """One control step; returns the direction acted on (+1/-1/0).
        Deterministic under an injected `now` for tests."""
        now = time.monotonic() if now is None else now
        self._adopt_addrs()
        # ONE forced registry re-list per step; every other view in
        # this poll reads the same listing (refresh=False) instead of
        # multiplying registry round-trips 4-8x per second
        listing = set(self.router.live_replicas())
        self._check_pending(now, listing)
        live = self.router.live_replicas(include_draining=False,
                                         refresh=False)
        self._m_live.set(len(live))
        with self._lock:
            pending = bool(self._pending)
        if pending:
            return 0  # a boot in flight: judge it before acting again
        if now < self._backoff_until:
            return 0  # crash-loop backoff window
        # the min-replica FLOOR is enforced here, not by the policy:
        # a replica dying outside a scale-in (OOM kill, hardware)
        # leaves a fleet whose signals look COLD (no traffic moves, so
        # no backlog and no p99), and the policy would idle at zero
        # forever.  Cooldown does not apply — restoring the floor is
        # repair, not scaling — but crash-loop backoff (above) does:
        # respawning a crash-looper in a tight loop is what the
        # detector exists to stop.
        if len(live) < self.policy.min_replicas:
            return (+1 if self._spawn(
                now, reason=f"below min_replicas="
                f"{self.policy.min_replicas} floor",
                live_before=listing) else 0)
        signals = self.router.signals(self.window_s)
        decision = self.policy.observe(signals, len(live), now)
        if decision > 0:
            return +1 if self._spawn(
                now, reason=self.policy.last_reason,
                live_before=listing) else 0
        if decision < 0:
            return -1 if self._scale_in(now, live) else 0
        return 0

    # -- spawn path ---------------------------------------------------------
    def ensure_min(self, timeout_s: Optional[float] = None) -> int:
        """Spawn until the fleet reaches the policy floor; with
        `timeout_s`, block until the spawned replicas are live (the
        cold-boot path of `cli autoscale`).  Returns how many were
        spawned."""
        n = 0
        live = self._live()
        while True:
            with self._lock:
                short = (len(live) + len(self._pending)
                         < self.policy.min_replicas)
            if not short:
                break
            if not self._spawn(time.monotonic(),
                               reason="ensure_min"):
                break
            n += 1
        if timeout_s is not None:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._pending:
                        break
                time.sleep(min(self.poll_s, 0.1))
                self._adopt_addrs()
                self._check_pending(time.monotonic())
        return n

    def _spawn(self, now: float, reason: str,
               live_before: Optional[set] = None) -> bool:
        try:
            fault_injector().fire("autoscaler.spawn")
        except Exception as e:
            self._spawn_failed(now, f"injected fault: {e!r}")
            return False
        if live_before is None:
            live_before = set(self.router.live_replicas())
        try:
            handle = self.launcher.spawn()
        except Exception as e:
            self._spawn_failed(now, f"launcher failed: {e!r}")
            return False
        with self._lock:
            self._pending.append((handle, now, live_before))
            self._m_desired.set(len(live_before) + len(self._pending))
        self._note(f"scale-out: spawning replica ({reason})")
        return True

    def _check_pending(self, now: float,
                       live: Optional[set] = None) -> None:
        with self._lock:
            if not self._pending:
                return
        if live is None:
            live = set(self.router.live_replicas())  # outside the lock
        with self._lock:
            entries = list(self._pending)
        credited: set = set()  # new members already matched this pass
        # members claimed by a sibling's BANNER address are never up
        # for fuzzy grabs either, regardless of processing order
        known = {h.addr for h, _, _ in entries if h.addr}
        for entry in entries:
            handle, t0, before = entry
            if handle.addr:
                joined = handle.addr in live
                if joined:
                    credited.add(handle.addr)
            else:
                # fuzzy pre-banner match: only a LIVE process may claim
                # a new registry member, and each member is credited to
                # at most one pending — a sibling's join must not
                # absorb a dead or still-booting spawn (that would
                # reset the crash streak and hide a crash-looping
                # replica behind its healthy neighbour)
                fresh = live - before - credited - known
                joined = bool(fresh) and handle.alive()
                if joined:
                    credited.add(sorted(fresh)[0])
            with self._lock:
                if entry not in self._pending:
                    continue  # a concurrent check already judged it
                if joined or not handle.alive() \
                        or now - t0 > self.spawn_timeout_s:
                    self._pending.remove(entry)
                else:
                    continue
                if joined:
                    if handle.addr:
                        self._owned[handle.addr] = handle
                    else:
                        self._unplaced.append(handle)
                    self._crash_streak = 0
            if joined:
                self._m_spawn_s.observe(now - t0)
                self._m_out.inc()
                self.policy.record_action(now)
                self._note(f"scale-out complete: replica "
                           f"{handle.addr or '?'} live after "
                           f"{now - t0:.1f}s")
            elif not handle.alive():
                self._spawn_failed(
                    now, f"replica pid {handle.pid} exited before "
                    "first serving")
            else:
                handle.kill()
                self._spawn_failed(
                    now, f"replica pid {handle.pid} not live within "
                    f"{self.spawn_timeout_s:.0f}s")

    def _spawn_failed(self, now: float, why: str) -> None:
        self._m_spawn_fails.inc()
        self._crash_streak += 1
        self.policy.record_action(now)  # failed boots also cool down
        if self._crash_streak >= self.crash_loop_limit:
            # crash loop: exponential backoff, alertable counter
            k = self._crash_streak - self.crash_loop_limit
            backoff = min(self.crash_backoff_s * (2 ** k),
                          self.crash_backoff_max_s)
            self._backoff_until = now + backoff
            self._crashloops += 1
            self._m_crashloops.inc()
            self._note(f"CRASH LOOP: {self._crash_streak} consecutive "
                       f"spawn failures ({why}); backing off "
                       f"{backoff:.0f}s")
        else:
            self._note(f"spawn failed ({self._crash_streak}/"
                       f"{self.crash_loop_limit}): {why}")

    # -- retire path --------------------------------------------------------
    def _pick_victim(self, live: List[str]) -> Optional[str]:
        """Least-outstanding live replica; prefer one we own (clean
        SIGTERM + reaped process) over an adopted one."""
        outstanding = self.router.stats()["replicas"]
        with self._lock:
            owned = set(self._owned)
        ranked = sorted(
            live, key=lambda a: (outstanding.get(a, 0),
                                 a not in owned))
        return ranked[0] if ranked else None

    def _scale_in(self, now: float, live: List[str]) -> bool:
        try:
            fault_injector().fire("autoscaler.drain")
        except Exception as e:
            self._m_aborts.inc()
            self._note(f"scale-in aborted (injected fault: {e!r})")
            return False
        victim = self._pick_victim(live)
        if victim is None:
            return False
        self.router.set_draining(victim, True)
        self._m_desired.set(max(len(live) - 1,
                                self.policy.min_replicas))
        self._note(f"scale-in: draining {victim} "
                   f"({self.policy.last_reason})")
        try:
            reply = replica_call(victim, {"op": "drain",
                                          "timeout": self.drain_grace_s},
                                 timeout_s=self.drain_grace_s + 10)
        except (OSError, ValueError) as e:
            # the victim died mid-drain: nothing left to retire — the
            # registry TTL reclaims it, the router resumes its streams
            self.router.set_draining(victim, False)
            self._m_aborts.inc()
            self._note(f"scale-in victim {victim} died mid-drain "
                       f"({e!r})")
            return False
        if not reply.get("drained"):
            # grace expired with accepted streams still running (or an
            # error reply): retiring now would cut them off mid-flight
            # — resume and try again when the replica is actually idle
            try:
                replica_call(victim, {"op": "resume"}, timeout_s=10)
            except (OSError, ValueError) as e:
                _LOG.warning("resume of %s failed: %r", victim, e)
            self.router.set_draining(victim, False)
            self._m_aborts.inc()
            self.policy.record_action(now)
            self._note(f"scale-in aborted: {victim} not drained "
                       f"within {self.drain_grace_s:.0f}s "
                       f"({reply.get('err', 'streams still active')})")
            return False
        # THE INVARIANT RE-CHECK: between the decision and the drain a
        # SIGKILL may have taken another replica.  Count the survivors
        # NOW — by PINGING them, not by trusting the registry: a
        # SIGKILLed replica stays listed until its lease TTL expires,
        # and counting that corpse would retire the victim into a
        # zero-replica fleet (test-pinned).  If retiring the (already
        # drained, still resumable) victim would leave the fleet below
        # the floor, resume it instead.
        survivors = []
        for a in self._live():
            if a == victim:
                continue
            try:
                if replica_call(a, {"op": "ping"},
                                timeout_s=5).get("ok"):
                    survivors.append(a)
            except (OSError, ValueError):
                continue  # dead or dying: not a survivor
        if len(survivors) < self.policy.min_replicas:
            try:
                replica_call(victim, {"op": "resume"}, timeout_s=10)
            except (OSError, ValueError) as e:
                _LOG.warning("resume of %s failed: %r", victim, e)
            self.router.set_draining(victim, False)
            self._m_aborts.inc()
            self.policy.record_action(now)
            self._note(
                f"scale-in aborted: only {len(survivors)} survivor(s) "
                f"left for min_replicas={self.policy.min_replicas} "
                "(a concurrent death raced the drain) — victim "
                "resumed")
            return False
        with self._lock:
            handle = self._owned.pop(victim, None)
        if handle is not None:
            handle.terminate()  # graceful: cli serve drains + delists
            try:
                handle.wait(timeout=self.drain_grace_s + 10)
            except Exception:
                handle.kill()
        else:
            try:
                replica_call(victim, {"op": "stop"}, timeout_s=10)
            except (OSError, ValueError):
                pass  # it stopped before replying: same outcome
        self.router.set_draining(victim, False)
        self._m_in.inc()
        self.policy.record_action(now)
        self._note(f"scale-in complete: {victim} retired")
        return True

    # -- introspection / lifecycle ------------------------------------------
    def status(self) -> Dict:
        live = self.router.live_replicas(include_draining=False)
        with self._lock:
            owned = sorted(self._owned)
            pending = len(self._pending)
            crash_streak = self._crash_streak
        return {
            "live": live,
            "pending_spawns": pending,
            "owned": owned,
            "crash_streak": crash_streak,
            "crashloops": self._crashloops,
            "backoff_s": max(0.0,
                             self._backoff_until - time.monotonic()),
            "min_replicas": self.policy.min_replicas,
            "max_replicas": self.policy.max_replicas,
            "last_event": self.last_event,
        }

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self.poll_s + 5)

    def close(self, retire_owned: bool = False) -> None:
        """Stop the loop; with `retire_owned`, SIGTERM every replica
        this autoscaler spawned (the `cli autoscale` exit path)."""
        self.stop()
        with self._lock:
            owned = list(self._owned.values()) + self._unplaced
            pending = [h for h, _, _ in self._pending]
            self._owned.clear()
            self._unplaced = []
            self._pending = []
        if retire_owned:
            for h in owned + pending:
                try:
                    h.terminate()
                except Exception as e:
                    _LOG.debug("terminate failed: %r", e)
            for h in owned + pending:
                try:
                    h.wait(timeout=self.drain_grace_s + 10)
                except Exception:
                    try:
                        h.kill()
                    except Exception as e:
                        _LOG.debug("kill failed: %r", e)
        for fam in (_M_LIVE, _M_DESIRED, _M_ABORTS, _M_CRASHLOOPS,
                    _M_SPAWN_FAILS, _M_SPAWN_S):
            fam.remove(scaler=self._sid)
        for direction in ("out", "in"):
            _M_EVENTS.remove(scaler=self._sid, direction=direction)
