"""Python side of the C inference API (native/src/capi.cc).

The reference ships a pure-C inference ABI
(/root/reference/paddle/capi/gradient_machine.h:
paddle_gradient_machine_create_for_inference_with_parameters + forward)
so C/C++/mobile hosts can embed trained models.  The TPU rebuild keeps the
C ABI but the engine behind it is this module: the .so embeds CPython,
loads the saved inference model (fluid.io.load_inference_model) and runs
it through the normal executor (XLA-compiled; CPU by default for embedded
hosts, TPU when PADDLE_TPU_CAPI_PLACE=tpu).

Handles are tracked in a registry keyed by integer id so the C side never
owns Python object lifetimes.
"""
from __future__ import annotations

import os
import threading
from typing import Dict

import numpy as np

__all__ = ["create", "feed", "run", "fetch", "destroy"]

_sessions: Dict[int, "InferenceSession"] = {}
_next_id = 1
_lock = threading.Lock()

_DTYPES = {0: np.float32, 1: np.int64, 2: np.int32}


class InferenceSession:
    def __init__(self, model_dir: str):
        import paddle_tpu as fluid

        place = (fluid.TPUPlace()
                 if os.environ.get("PADDLE_TPU_CAPI_PLACE") == "tpu"
                 else fluid.CPUPlace())
        self.exe = fluid.Executor(place)
        self.scope = fluid.Scope()
        (self.program, self.feed_names,
         self.fetch_vars) = fluid.io.load_inference_model(
            model_dir, self.exe, scope=self.scope)
        self.feeds: Dict[str, np.ndarray] = {}
        self.results = []

    def feed(self, name: str, payload: bytes, dtype_code: int, dims):
        arr = np.frombuffer(payload, dtype=_DTYPES[dtype_code])
        self.feeds[name] = arr.reshape([int(d) for d in dims]).copy()

    def run(self) -> int:
        missing = [n for n in self.feed_names if n not in self.feeds]
        if missing:
            raise ValueError(f"missing feeds: {missing}")
        self.results = [
            np.asarray(r) for r in self.exe.run(
                self.program, feed=dict(self.feeds),
                fetch_list=self.fetch_vars, scope=self.scope)
        ]
        return len(self.results)

    def fetch(self, idx: int):
        r = np.ascontiguousarray(self.results[idx], dtype=np.float32)
        return r.tobytes(), list(r.shape)


def create(model_dir: str) -> int:
    global _next_id
    s = InferenceSession(model_dir)
    with _lock:
        sid = _next_id
        _next_id += 1
        _sessions[sid] = s
    return sid


def feed(sid: int, name: str, payload: bytes, dtype_code: int,
         dims) -> None:
    _sessions[sid].feed(name, payload, dtype_code, dims)


def run(sid: int) -> int:
    return _sessions[sid].run()


def fetch(sid: int, idx: int):
    return _sessions[sid].fetch(idx)


def destroy(sid: int) -> None:
    with _lock:
        _sessions.pop(sid, None)
