"""Event-driven trainer conveniences atop the fluid-style API.

Reference: /root/reference/python/paddle/v2/trainer.py (SGD.train :137-216,
test :218) and v2/event.py (BeginPass/EndPass/BeginIteration/EndIteration
callbacks).  The v2 gserver machinery is not rebuilt (SURVEY.md §7 hard
part 7); these are the same user-facing conveniences expressed over
Program + Executor.
"""
from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

import numpy as np

from .core.executor import CPUPlace, Executor, _to_numpy
from .core.flags import get_flag
from .core.framework import (
    Program,
    default_main_program,
    default_startup_program,
)
from .data_feeder import DataFeeder
from .observability import attribution as obs_attr
from .observability import flightrecorder
from .observability import metrics as obs_metrics
from .observability import tracing as obs_tracing

# train-loop telemetry (docs/observability.md): gated by
# PADDLE_TPU_METRICS, so the serial loop's semantics and cost are
# untouched when off
_M_STEPS = obs_metrics.counter(
    "paddle_tpu_trainer_steps_total", "training steps completed")
_M_EXAMPLES = obs_metrics.counter(
    "paddle_tpu_trainer_examples_total",
    "examples consumed (leading dim of the first feed value)")
_M_STEP_SECONDS = obs_metrics.histogram(
    "paddle_tpu_trainer_step_seconds",
    "train-loop iteration wall latency (feed ready -> dispatch done)")
_M_COST = obs_metrics.gauge(
    "paddle_tpu_trainer_last_cost", "most recently materialized cost")
_M_FETCH_SYNC = obs_metrics.histogram(
    "paddle_tpu_trainer_fetch_sync_seconds",
    "blocking device->host fetch-sync stalls (LazyFetch reads)")


def _feed_batch_size(feed) -> int:
    """Leading dim of the first feed value (0 when indeterminable)."""
    if isinstance(feed, dict) and feed:
        v = next(iter(feed.values()))
        v = getattr(v, "data", v)  # LoDTensor wrapper
        shape = getattr(v, "shape", None)
        if shape:
            return int(shape[0])
    return 0

__all__ = [
    "infer",
    "BeginPass",
    "EndPass",
    "BeginIteration",
    "EndIteration",
    "LazyFetch",
    "Trainer",
]


class LazyFetch:
    """Handle for a fetched value that may still be in flight on device.

    `Executor.run(..., return_numpy=True)` forces a blocking device->host
    copy of every fetch — with async dispatch that serializes the loop on
    the device.  A LazyFetch wraps the raw device value instead; the copy
    happens only when someone actually reads it (`float()`,
    `np.asarray(...)`, `.numpy()`), so step N+1 can dispatch while step N
    is still computing.  Reading is idempotent (the materialized host
    value is cached)."""

    __slots__ = ("_device_value", "_host_value")

    def __init__(self, device_value):
        self._device_value = device_value
        self._host_value = None

    def value(self):
        """The raw value, no sync: device-resident until materialized,
        the cached host copy afterwards."""
        if self._host_value is not None:
            return self._host_value
        return self._device_value

    def numpy(self):
        """Materialize on host (blocks until the computation delivers).
        Releases the device buffer: a pass worth of retained cost
        handles must not pin one live device array per step."""
        if self._host_value is None:
            from . import profiler

            with profiler.record_event("pipeline.fetch_sync"):
                t0 = time.perf_counter()
                self._host_value = _to_numpy(self._device_value)
                _M_FETCH_SYNC.observe(time.perf_counter() - t0)
            self._device_value = None
        return self._host_value

    def __float__(self):
        return float(np.asarray(self.numpy()).reshape(-1)[0])

    def __array__(self, dtype=None):
        arr = np.asarray(self.numpy())
        return arr.astype(dtype) if dtype is not None else arr

    def __format__(self, spec):
        # format(x, "") must equal str(x): plain f-string interpolation
        # of event.cost is a read, and reads materialize
        return format(float(self), spec)

    def __repr__(self):
        if self._host_value is not None:
            return f"LazyFetch({self._host_value!r})"
        return "LazyFetch(<in flight>)"

    # float-like protocol: existing EndIteration handlers do arithmetic,
    # comparisons and printing on event.cost — each such read IS the
    # materialization point (Python never falls back to __float__ for
    # operators, so these must be explicit)
    @staticmethod
    def _f(other):
        return float(other) if isinstance(other, LazyFetch) else other

    def __str__(self):
        return str(float(self))

    def __bool__(self):
        return bool(float(self))

    def __hash__(self):
        return hash(float(self))

    def __eq__(self, other):
        return float(self) == self._f(other)

    def __lt__(self, other):
        return float(self) < self._f(other)

    def __le__(self, other):
        return float(self) <= self._f(other)

    def __gt__(self, other):
        return float(self) > self._f(other)

    def __ge__(self, other):
        return float(self) >= self._f(other)

    def __add__(self, other):
        return float(self) + self._f(other)

    __radd__ = __add__

    def __sub__(self, other):
        return float(self) - self._f(other)

    def __rsub__(self, other):
        return self._f(other) - float(self)

    def __mul__(self, other):
        return float(self) * self._f(other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return float(self) / self._f(other)

    def __rtruediv__(self, other):
        return self._f(other) / float(self)

    def __neg__(self):
        return -float(self)

    def __abs__(self):
        return abs(float(self))


class BeginPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass:
    def __init__(self, pass_id, metrics=None):
        self.pass_id = pass_id
        self.metrics = metrics


class BeginIteration:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration:
    def __init__(self, pass_id, batch_id, cost, metrics=None):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        self.metrics = metrics


class Trainer:
    """Pass/batch loop with event callbacks (reference v2 SGD.train shape,
    fluid executor underneath)."""

    def __init__(self, loss, optimizer=None, place=None, feed_list=None,
                 main_program: Optional[Program] = None,
                 startup_program: Optional[Program] = None,
                 fetch_list: Optional[Sequence] = None):
        self.loss = loss
        self.place = place or CPUPlace()
        self.main_program = main_program or default_main_program()
        self.startup_program = startup_program or default_startup_program()
        self.feed_list = feed_list
        self.fetch_list = list(fetch_list or [])
        if optimizer is not None and not self._has_optimize_ops():
            optimizer.minimize(loss, startup_program=self.startup_program)
        self.exe = Executor(self.place)
        self._started = False

    def _has_optimize_ops(self):
        opt_types = {"sgd", "momentum", "adam", "adamax", "adagrad",
                     "adadelta", "decayed_adagrad", "ftrl", "rmsprop"}
        return any(op.type in opt_types
                   for op in self.main_program.global_block().ops)

    def _feeder(self):
        if self.feed_list is None:
            raise ValueError("Trainer needs feed_list to build a DataFeeder")
        return DataFeeder(feed_list=self.feed_list, place=self.place)

    def start(self):
        if not self._started:
            self.exe.run(self.startup_program)
            self._started = True

    def train(self, num_passes: int, reader: Callable,
              event_handler: Optional[Callable] = None,
              feeder: Optional[DataFeeder] = None,
              checkpoint_dir: Optional[str] = None,
              checkpoint_every_n_passes: int = 1,
              checkpoint_max_keep: int = 3,
              checkpoint_every_n_iters: int = 0,
              resume_from: Optional[str] = None,
              prefetch: Optional[int] = None,
              sync_every_n: Optional[int] = None,
              cluster=None):
        """reader: batch reader (yields lists of samples per batch).

        With `checkpoint_dir`, resumes from the newest valid snapshot there
        (params + optimizer state + the pass/batch/step cursor travel in
        the snapshot meta) and saves a snapshot every
        `checkpoint_every_n_passes` (<= 0 disables saving) —
        the trainer-side analogue of the Go pserver's periodic checkpoint
        (go/pserver/service.go:120-203) and the book_distribute scripts'
        per-pass save.

        Auto-resume mode: `checkpoint_every_n_iters > 0` additionally
        snapshots every N iterations, and `resume_from=dir` restores
        params + the global step from the newest valid snapshot there and
        CONTINUES THE PASS it died in (already-trained batches of that
        pass are fast-forwarded, relying on the deterministic reader) —
        so a trainer killed at iteration k and restarted under a
        supervisor finishes with the same step count and params as an
        uninterrupted run.  `resume_from` doubles as the save target when
        `checkpoint_dir` is not given.  The running step count is exposed
        as `self.step`.

        Async hot path: `prefetch=N` (default flag `prefetch_depth`, env
        PADDLE_TPU_PREFETCH_DEPTH) runs reader + feed packing + H2D on a
        background thread N batches ahead (reader/pipeline.py);
        `sync_every_n=K` (default flag `sync_every_n`, env
        PADDLE_TPU_SYNC_EVERY_N) > 1 threads the cost through
        `EndIteration` as a `LazyFetch` that materializes only when the
        callback reads it (or every K steps, bounding the in-flight
        dispatch queue), so step N+1 dispatches while step N computes.
        Both default off/1: the default loop is bit-for-bit the serial
        one, and the async loop runs the SAME ops in the SAME order, so
        final parameters are bit-identical (test-enforced,
        tests/test_async_feed.py).

        Elastic clusters: `cluster=` (a cloud.cluster.ClusterClient,
        an in-process ClusterController, or a controller address
        string — docs/resilience.md "Elastic clusters") arms the
        process-wide view subscription, registers this trainer as a
        TTL-leased cluster member for the duration of the loop, and
        publishes the program's send-op param descs to the controller
        (idempotent — first definition wins).  The program's send/recv
        rounds then resolve endpoints through the controller's current
        view and survive pserver membership changes without a restart;
        the lease, released on clean exit (or expired by TTL on a
        crash), is what lets the controller shrink fan-in and the
        master reclaim this trainer's task chunks."""
        from . import io
        from .core.resilience import fault_injector
        from .reader.pipeline import prefetch_feeder

        self.start()
        lease = None
        prev_cluster = client = None
        armed = False
        try:
            if cluster is not None:
                from .parallel.comm import get_cluster, set_cluster

                # the subscription is process-global: remember what was
                # armed before (usually nothing) and restore it on
                # exit, so a later train()/executor run in this process
                # does not route rounds through a controller that may
                # be gone.  Arming INSIDE the try: if define()/join()
                # fail against an unreachable controller, the finally
                # still restores the prior subscription instead of
                # leaving every later non-elastic run routed at the
                # dead address
                prev_cluster = get_cluster()
                client = set_cluster(cluster)
                armed = True
                descs = self._send_param_descs()
                if descs:
                    client.define(descs)
                lease = client.join("trainer")
            return self._train_loop(
                num_passes, reader, event_handler, feeder,
                checkpoint_dir, checkpoint_every_n_passes,
                checkpoint_max_keep, checkpoint_every_n_iters,
                resume_from, prefetch, sync_every_n, io,
                fault_injector, prefetch_feeder)
        finally:
            if lease is not None:
                lease.release()
            if armed:
                from .parallel.comm import set_cluster

                set_cluster(prev_cluster)
                if client is not cluster and client is not prev_cluster:
                    # we built this ClusterClient from an address /
                    # controller the caller passed; callers who pass a
                    # client keep ownership of theirs
                    try:
                        client.close()
                    except Exception:
                        pass

    def _send_param_descs(self):
        """VarDescs of the params this program's send ops place (the
        fused send's Out list), for ClusterClient.define — shapes come
        from the program vars so the controller's balanced_split can
        weigh bytes."""
        from .parallel.distributed_spliter import VarDesc

        blk = self.main_program.global_block()
        descs = []
        for op in blk.ops:
            if op.type != "send":
                continue
            for name in op.output("Out"):
                v = blk.vars.get(name)
                descs.append(VarDesc(
                    name, tuple(getattr(v, "shape", None) or ()),
                    str(getattr(v, "dtype", "float32"))))
        return descs

    def _train_loop(self, num_passes, reader, event_handler, feeder,
                    checkpoint_dir, checkpoint_every_n_passes,
                    checkpoint_max_keep, checkpoint_every_n_iters,
                    resume_from, prefetch, sync_every_n, io,
                    fault_injector, prefetch_feeder):
        event_handler = event_handler or (lambda e: None)
        feeder = feeder or self._feeder()
        fetches = [self.loss] + self.fetch_list
        # fleet telemetry: with PADDLE_TPU_TELEMETRY_REGISTRY set, the
        # trainer publishes its /metrics endpoint for the
        # TelemetryCollector (no-op otherwise; lazy import keeps the
        # cloud registry out of plain local runs)
        from .observability.collector import maybe_announce

        maybe_announce("trainer")
        if prefetch is None:
            prefetch = int(get_flag("prefetch_depth"))
        if sync_every_n is None:
            sync_every_n = int(get_flag("sync_every_n"))
        sync_every_n = max(int(sync_every_n), 1)
        lazy = sync_every_n > 1
        def make_feeds(rd):
            if prefetch > 0:
                # feed_pack/h2d attribution happens on the prefetch
                # worker (reader/pipeline.py)
                return prefetch_feeder(rd, feeder, self.place,
                                       depth=prefetch)()

            def packed():
                for b in rd():
                    with obs_attr.phase("trainer", "feed_pack"):
                        feed = feeder.feed(b)
                    yield feed
            return packed()
        self._publish_static_floor()
        if resume_from is not None and checkpoint_dir is None:
            checkpoint_dir = resume_from
        first_pass, skip_batches = 0, 0
        self.step = int(getattr(self, "step", 0))
        load_dir = resume_from if resume_from is not None else checkpoint_dir
        if load_dir is not None:
            meta = io.load_checkpoint(self.exe, load_dir,
                                      main_program=self.main_program)
            if meta is not None:
                args = meta["trainer_args"]
                first_pass = int(args.get("next_pass_id", 0))
                skip_batches = int(args.get("next_batch_id", 0))
                self.step = int(args.get("step", self.step))

        def _save(next_pass_id, next_batch_id):
            io.save_checkpoint(
                self.exe, checkpoint_dir,
                main_program=self.main_program,
                trainer_args={"next_pass_id": next_pass_id,
                              "next_batch_id": next_batch_id,
                              "step": self.step},
                max_keep=checkpoint_max_keep)

        _no_batch = object()
        for pass_id in range(first_pass, num_passes):
            # in a resumed pass, BeginPass fires only once a batch
            # actually trains: a snapshot taken at the pass's final batch
            # would otherwise replay the whole pass as skips and emit a
            # duplicate BeginPass/EndPass pair (the latter with NaN cost)
            n_skip = skip_batches
            skip_batches = 0
            resuming = n_skip > 0
            trained = False
            if not resuming:
                event_handler(BeginPass(pass_id))
            pass_costs = []
            if resuming:
                # resumed mid-pass: the snapshot already carries the
                # effect of the skipped batches; replay the RAW reader
                # past them (no feed packing, no H2D — restart latency
                # must not scale with feed-pack cost of the prefix)
                def pass_reader(_n=n_skip):
                    it = iter(reader())
                    for _ in range(_n):
                        if next(it, _no_batch) is _no_batch:
                            return
                    yield from it
            else:
                pass_reader = reader
            feeds = make_feeds(pass_reader)
            try:
                for batch_id, feed in enumerate(feeds, start=n_skip):
                    if resuming and not trained:
                        event_handler(BeginPass(pass_id))
                    trained = True
                    # chaos hook: auto-resume tests kill the trainer here
                    fault_injector().fire("trainer.iteration")
                    event_handler(BeginIteration(pass_id, batch_id))
                    t_step = time.perf_counter()
                    with obs_tracing.span("trainer.step",
                                          pass_id=pass_id,
                                          batch_id=batch_id):
                        with obs_attr.phase("trainer", "compute"):
                            outs = self.exe.run(
                                self.main_program, feed=feed,
                                fetch_list=fetches,
                                return_numpy=not lazy)
                    if lazy:
                        cost = LazyFetch(outs[0])
                        # metrics stay RAW device arrays: jax arrays are
                        # already lazy (async dispatch) and keep
                        # elementwise semantics — a LazyFetch wrapper
                        # would collapse vector metrics to [0] under
                        # arithmetic.  LazyFetch is for the scalar cost
                        metrics = list(outs[1:])
                    else:
                        cost = float(np.asarray(outs[0]).reshape(-1)[0])
                        metrics = outs[1:]
                    pass_costs.append(cost)
                    self.step += 1
                    if flightrecorder.armed():
                        # the post-mortem ring wants the step cadence
                        # (cost may still be device-lazy — not forced)
                        flightrecorder.note(
                            "trainer.step", step=self.step,
                            pass_id=pass_id, batch_id=batch_id)
                    if obs_metrics.enabled():
                        _M_STEPS.inc()
                        _M_STEP_SECONDS.observe(
                            time.perf_counter() - t_step)
                        bs = _feed_batch_size(feed)
                        if bs:
                            _M_EXAMPLES.inc(bs)
                        if not lazy:
                            _M_COST.set(cost)
                    if lazy and self.step % sync_every_n == 0:
                        # periodic fence: bounds the in-flight dispatch
                        # queue, surfaces device errors at a bounded
                        # distance from their step, and releases the
                        # window's cost device buffers (numpy() drops
                        # the handle) so a long pass doesn't pin one
                        # live device array per trained step
                        for c in pass_costs[-sync_every_n:]:
                            if isinstance(c, LazyFetch):
                                c.numpy()
                        if obs_metrics.enabled() and pass_costs:
                            _M_COST.set(float(pass_costs[-1]))
                    event_handler(EndIteration(pass_id, batch_id, cost,
                                               metrics=metrics))
                    if checkpoint_dir is not None \
                            and checkpoint_every_n_iters > 0 \
                            and self.step % checkpoint_every_n_iters == 0:
                        _save(pass_id, batch_id + 1)
            finally:
                # a prefetching iterator owns a worker thread: an
                # exception mid-pass must not leak it blocked on the queue
                if hasattr(feeds, "close"):
                    feeds.close()
            if resuming and not trained:
                # the snapshot was taken AT the pass boundary: this pass
                # is already complete, so no events and no redundant
                # checkpoint for it — move straight to the next pass
                continue
            event_handler(EndPass(pass_id, metrics={
                "avg_cost": float(np.mean([float(c) for c in pass_costs]))
                if pass_costs else float("nan")}))
            if checkpoint_dir is not None and checkpoint_every_n_passes > 0 \
                    and (pass_id + 1) % checkpoint_every_n_passes == 0:
                _save(pass_id + 1, 0)

    def _publish_static_floor(self):
        """Static roofline floor for the compute phase, for the
        collector's calibration-drift detector (docs/observability.md
        "Time attribution").  Best-effort and gated: never slows or
        breaks an uninstrumented run."""
        if not obs_metrics.enabled():
            return
        try:
            from .analysis.cost_model import (estimate_program,
                                              roofline_seconds)
            est = estimate_program(self.main_program)
            obs_attr.publish_static_floor("trainer", {
                "compute": roofline_seconds(est.total_flops,
                                            est.total_bytes),
            })
        except Exception:
            pass

    def test(self, reader: Callable, feeder: Optional[DataFeeder] = None,
             fetch_list: Optional[Sequence] = None):
        """Average fetched values over a reader using the inference clone
        of the program (is_test behavior for dropout/batch_norm)."""
        self.start()
        feeder = feeder or self._feeder()
        fetches = list(fetch_list or [self.loss] + self.fetch_list)
        test_prog = self.main_program.clone(for_test=True)
        totals, n = None, 0
        for batch in reader():
            outs = self.exe.run(test_prog, feed=feeder.feed(batch),
                                fetch_list=fetches)
            vals = [float(np.asarray(o).reshape(-1)[0]) for o in outs]
            totals = vals if totals is None else [
                a + b for a, b in zip(totals, vals)]
            n += 1
        return [t / max(n, 1) for t in (totals or [])]

    def save_params(self, dirname):
        from . import io

        self.start()
        io.save_persistables(self.exe, dirname,
                             main_program=self.main_program)

    def save_inference_model(self, dirname, feeded_var_names, target_vars):
        from . import io

        self.start()
        io.save_inference_model(dirname, feeded_var_names, target_vars,
                                self.exe, main_program=self.main_program)


def infer(output, feed, program=None, scope=None, place=None,
          return_numpy=True):
    """One-shot inference on trained parameters (reference
    python/paddle/v2/inference.py `paddle.infer(output_layer=..., input=...)`
    — here parameters come from the scope instead of a Parameters pack).

        probs = fluid.trainer.infer(predict_var, {"img": batch})
    """
    from .io import get_inference_program

    outputs = output if isinstance(output, (list, tuple)) else [output]
    if program is None and hasattr(outputs[0], "block"):
        # default to the program that OWNS the output var (the ambient
        # default program is usually not the one built under program_guard)
        program = outputs[0].block.program
    prog = get_inference_program(outputs, program)
    exe = Executor(place) if place is not None else Executor(CPUPlace())
    res = exe.run(prog, feed=feed,
                  fetch_list=[o.name if hasattr(o, "name") else str(o)
                              for o in outputs],
                  scope=scope, return_numpy=return_numpy)
    return res[0] if not isinstance(output, (list, tuple)) else res
