"""Stateful evaluators accumulating metrics across mini-batches.

Reference: /root/reference/python/paddle/v2/fluid/evaluator.py:1-267 —
Evaluator base keeps persistable state vars updated by ops appended to the
main program; `eval()` builds a small program computing the metric from the
accumulated states; `reset()` zeroes them.
"""
from __future__ import annotations

import numpy as np

from . import layers
from .core.framework import (
    Program,
    default_main_program,
    default_startup_program,
    unique_name,
)

__all__ = ["Evaluator", "Accuracy", "ChunkEvaluator"]


class Evaluator:
    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper_name = unique_name(name)
        self.main_program = kwargs.get("main_program") or \
            default_main_program()
        self.startup_program = kwargs.get("startup_program") or \
            default_startup_program()

    def _create_state(self, suffix, dtype, shape):
        """Persistable accumulator var, zero-initialized in the startup
        program (reference evaluator.py _create_state)."""
        name = unique_name(f"{self.helper_name}.{suffix}")
        state = self.main_program.global_block().create_var(
            name=name, shape=shape, dtype=dtype, persistable=True)
        sb = self.startup_program.global_block()
        sb.create_var(name=name, shape=shape, dtype=dtype, persistable=True)
        sb.append_op("fill_constant", {}, {"Out": [name]},
                     {"shape": list(shape), "dtype": dtype, "value": 0.0})
        self.states.append(state)
        return state

    def reset(self, executor, reset_program=None):
        if reset_program is None:
            reset_program = Program()
        block = reset_program.global_block()
        for state in self.states:
            block.create_var(name=state.name, shape=state.shape,
                             dtype=state.dtype, persistable=True)
            block.append_op("fill_constant", {}, {"Out": [state.name]},
                            {"shape": list(state.shape),
                             "dtype": state.dtype, "value": 0.0})
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError

    def _accumulate(self, state, delta):
        """state += delta inside the main program (persistable write)."""
        block = self.main_program.global_block()
        tmp = block.create_var(name=unique_name(state.name + ".acc"),
                               dtype=state.dtype)
        block.append_op("elementwise_add",
                        {"X": [state.name], "Y": [delta.name]},
                        {"Out": [tmp.name]})
        block.append_op("assign", {"X": [tmp.name]}, {"Out": [state.name]})


class Accuracy(Evaluator):
    """Accumulated classification accuracy (reference evaluator.py Accuracy)."""

    def __init__(self, input, label, k=1, **kwargs):
        super().__init__("accuracy", **kwargs)
        self.total = self._create_state("total", "float32", (1,))
        self.correct = self._create_state("correct", "float32", (1,))
        block = self.main_program.current_block
        correct = block.create_var(name=unique_name("acc_correct"),
                                   dtype="int32", stop_gradient=True)
        total = block.create_var(name=unique_name("acc_total"),
                                 dtype="int32", stop_gradient=True)
        acc = layers.accuracy(input=input, label=label, k=k,
                              correct=correct, total=total)
        self._accumulate(self.total, layers.cast(total, "float32"))
        self._accumulate(self.correct, layers.cast(correct, "float32"))
        self.metrics.append(acc)

    def eval(self, executor, eval_program=None):
        if eval_program is None:
            eval_program = Program()
        block = eval_program.global_block()
        for state in (self.total, self.correct):
            block.create_var(name=state.name, shape=state.shape,
                             dtype=state.dtype, persistable=True)
        out = block.create_var(name=unique_name("accuracy_out"),
                               dtype="float32")
        block.append_op("elementwise_div",
                        {"X": [self.correct.name], "Y": [self.total.name]},
                        {"Out": [out.name]})
        return executor.run(eval_program, fetch_list=[out.name])[0]


class ChunkEvaluator(Evaluator):
    """Accumulated chunk P/R/F1 (reference evaluator.py ChunkEvaluator)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, **kwargs):
        super().__init__("chunk_eval", **kwargs)
        self.num_infer_chunks = self._create_state(
            "num_infer_chunks", "float32", (1,))
        self.num_label_chunks = self._create_state(
            "num_label_chunks", "float32", (1,))
        self.num_correct_chunks = self._create_state(
            "num_correct_chunks", "float32", (1,))
        (precision, recall, f1, num_infer, num_label,
         num_correct) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        self._accumulate(self.num_infer_chunks,
                         layers.cast(num_infer, "float32"))
        self._accumulate(self.num_label_chunks,
                         layers.cast(num_label, "float32"))
        self._accumulate(self.num_correct_chunks,
                         layers.cast(num_correct, "float32"))
        self.metrics.extend([precision, recall, f1])

    def eval(self, executor, eval_program=None):
        if eval_program is None:
            eval_program = Program()
        block = eval_program.global_block()
        for state in self.states:
            block.create_var(name=state.name, shape=state.shape,
                             dtype=state.dtype, persistable=True)
        ni = block.var(self.num_infer_chunks.name)
        nl = block.var(self.num_label_chunks.name)
        nc = block.var(self.num_correct_chunks.name)
        # metric math as a tiny program
        from .core.framework import program_guard

        with program_guard(eval_program, Program()):
            precision = layers.elementwise_div(
                layers.cast(nc, "float32"),
                layers.elementwise_max(
                    layers.cast(ni, "float32"),
                    layers.fill_constant(shape=[1], dtype="float32",
                                         value=1e-6)))
            recall = layers.elementwise_div(
                layers.cast(nc, "float32"),
                layers.elementwise_max(
                    layers.cast(nl, "float32"),
                    layers.fill_constant(shape=[1], dtype="float32",
                                         value=1e-6)))
            two_pr = layers.scale(
                layers.elementwise_mul(precision, recall), scale=2.0)
            f1 = layers.elementwise_div(
                two_pr,
                layers.elementwise_max(
                    layers.elementwise_add(precision, recall),
                    layers.fill_constant(shape=[1], dtype="float32",
                                         value=1e-6)))
        p, r, f = executor.run(
            eval_program, fetch_list=[precision, recall, f1])
        return np.asarray([p[0], r[0], f[0]], np.float32)
