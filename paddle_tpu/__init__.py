"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of early-2018 PaddlePaddle "Fluid" (reference: /root/reference).

Architecture (see /root/repo/SURVEY.md for the reference map):
  * Program/Block/Op/Var IR built by a Python DSL (core/framework.py)
  * dual executor: op-by-op interpreter for debugging + whole-block XLA
    compilation with an executable cache (core/executor.py)
  * autodiff by op-desc rewriting with generic-VJP grad ops (backward.py)
  * op corpus lowered to jax/lax; conv/matmul ride the MXU, collectives
    ride ICI via the parallel package
"""
from . import (  # noqa: F401
    amp,
    analysis,
    observability,
    profiler,
    clip,
    concurrency,
    debugger,
    evaluator,
    image,
    initializer,
    io,
    layers,
    learning_rate_decay,
    nets,
    plot,
    regularizer,
    serving,
)
from .clip import (  # noqa: F401
    ErrorClipByValue,
    GradientClipByGlobalNorm,
    GradientClipByNorm,
    GradientClipByValue,
)
from .backward import append_backward, calc_gradient, gradients  # noqa: F401
from .core import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Executor,
    LoDTensor,
    Program,
    Scope,
    SelectedRows,
    TPUPlace,
    Variable,
    create_lod_tensor,
    default_main_program,
    default_startup_program,
    global_scope,
    program_guard,
)
from . import optimizer  # noqa: F401
from .optimizer import (  # noqa: F401
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    DecayedAdagrad,
    Ftrl,
    Momentum,
    RMSProp,
)
from .concurrency import (  # noqa: F401
    Go,
    channel_close,
    channel_recv,
    channel_send,
    go,
    make_channel,
)
from .data_feeder import DataFeeder  # noqa: F401
from .parameters import Parameters  # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from .core.executor import scope_guard, switch_scope  # noqa: F401
from .core.framework import (  # noqa: F401
    Block,
    Operator,
    pipeline_stage,
)
from .core.lod import Tensor  # noqa: F401
from .memory_optimization_transpiler import memory_optimize  # noqa: F401
from .parallel.executor import (  # noqa: F401
    DistributeTranspiler,
    ParallelExecutor,
    ShardingTranspiler,
    SimpleDistributeTranspiler,
)
from .parallel.pipeline_program import PipelineExecutor  # noqa: F401

__version__ = "0.1.0"
