"""Protocol models for the deterministic-schedule checker.

Each model is a small, faithful re-statement of one of the distributed
runtime's concurrency protocols, written against plain `threading`
primitives so schedcheck.explore() can serialize it and search
interleavings.  They are MODELS, not mocks-of-everything: where the real
state machine is pure host-side Python the real class is used directly
(the GenerationServer model drives the real serving.kv_cache.
PagedKVCache, so the KV-block refcount-balance invariant checks the
production accounting, not a toy).

Every model returns a state dict; its paired invariant raises on a bad
terminal state.  `PROTOCOLS` maps protocol name -> (model_factory,
invariant) for the CLI (`cli concurrency --sched`) and CI; each factory
also takes `buggy=True` to reintroduce a characteristic historical bug
shape, which the checker must then FIND — that is tested, so the models
cannot rot into always-green.

Checked invariants (ISSUE 13 acceptance):
  * fence_migrate_commit — no deadlock; NO LOST SHARD COPY: every
    placed param has a confirmed holder after COMMIT (buggy=True drops
    the last copy before the new owner confirmed, the exact shape PR 7
    review-hardening fixed with `owner_ok`);
  * elastic_round — a mid-round endpoint death is replayed against the
    next view: every grad applied at-least-once, the round terminates
    (buggy=True replays against the STALE view — the round wedges);
  * generation_admit_finish_swap — admit/finish/hot-swap over the REAL
    PagedKVCache keeps KV-BLOCK REFCOUNT BALANCE: after drain +
    flush_prefix the pool is fully free and no live refs remain
    (buggy=True skips release on a finish that lands mid-drain);
  * comm_send_round — two caller threads sharing the pool never
    interleave one endpoint's frames (the per-endpoint worker is what
    serializes them; buggy=True writes to the shared socket directly).
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Tuple

__all__ = [
    "PROTOCOLS",
    "model_fence_migrate_commit",
    "invariant_fence_migrate_commit",
    "model_elastic_round",
    "invariant_elastic_round",
    "model_generation_admit_finish_swap",
    "invariant_generation_admit_finish_swap",
    "model_comm_send_round",
    "invariant_comm_send_round",
]


# ---------------------------------------------------------------------------
# FENCE -> MIGRATE -> COMMIT (cloud/cluster.py two-phase rebalance)
# ---------------------------------------------------------------------------


def model_fence_migrate_commit(buggy: bool = False):
    """Pserver B dies holding the only pserver copy of shard "v"; the
    controller fences the survivor, recovers "v" from the trainer-held
    copy, commits a new view.  A trainer keeps running rounds
    throughout, waiting out the fence.

    buggy=True: the trainer-held copy is DISCARDED before the push to
    the new owner is confirmed, while the first push attempt fails —
    the shard is lost for good under the schedule where the failure
    interleaves before the drop (the PR 7 `owner_ok` bug shape)."""

    def run():
        cond = threading.Condition()
        state = {
            "view": {"epoch": 1, "place": {"w": "A", "v": "B"}},
            "servers": {
                "A": {"fenced": False, "shards": {"w": 10}},
                "B": {"fenced": False, "shards": {"v": 20},
                      "dead": False},
            },
            "trainer_copies": {"v": 20},
            "push_attempts": [0],
            "rounds_done": 0,
            "lost": [],
        }
        servers = state["servers"]

        def push_to_owner(name, value, owner):
            """Trainer-held recovery push; the FIRST attempt fails
            (dead-connection shape the controller must tolerate)."""
            state["push_attempts"][0] += 1
            if state["push_attempts"][0] == 1:
                return False
            with cond:
                servers[owner]["shards"][name] = value
            return True

        def controller():
            with cond:
                servers["B"]["dead"] = True
                state["view"] = {"epoch": 2, "status": "rebalancing",
                                 "place": {"w": "A", "v": "A"}}
                servers["A"]["fenced"] = True
                cond.notify_all()
            # MIGRATE: dead B's shard "v" must land on A.  Source: the
            # trainer-held copy (B is gone, no snapshot in this model).
            copy = state["trainer_copies"].get("v")
            owner_ok = False
            if copy is not None:
                if buggy:
                    # drop the last copy BEFORE the push is confirmed
                    state["trainer_copies"].pop("v", None)
                ok = push_to_owner("v", copy, "A")
                if not ok:
                    # retry against the (still-held) trainer copy —
                    # exactly what the buggy variant just threw away
                    copy2 = state["trainer_copies"].get("v")
                    if copy2 is not None:
                        ok = push_to_owner("v", copy2, "A")
                owner_ok = ok and "v" in servers["A"]["shards"]
            if not owner_ok:
                state["lost"].append("v")
            if not buggy:
                state["trainer_copies"].pop("v", None)
            # COMMIT
            with cond:
                servers["A"]["fenced"] = False
                state["view"] = {"epoch": 3, "status": "stable",
                                 "place": {"w": "A", "v": "A"}}
                cond.notify_all()

        def trainer():
            for _ in range(2):
                while True:
                    with cond:
                        view = state["view"]
                        owner = view["place"]["w"]
                        if servers[owner].get("dead") \
                                or servers[owner]["fenced"]:
                            # fenced/dead: wait for the next view
                            cond.wait()
                            continue
                        servers[owner]["shards"]["w"] += 1
                        state["rounds_done"] += 1
                        break

        ts = [threading.Thread(target=controller),
              threading.Thread(target=trainer)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return state

    return run


def invariant_fence_migrate_commit(state):
    assert not state["lost"], f"lost shard copies: {state['lost']}"
    view = state["view"]
    assert view["status"] == "stable", view
    for name, owner in view["place"].items():
        assert name in state["servers"][owner]["shards"], \
            f"{name} not held by its placed owner {owner}"
    assert state["rounds_done"] == 2, state["rounds_done"]


# ---------------------------------------------------------------------------
# elastic_round retry/replay (parallel/comm.py)
# ---------------------------------------------------------------------------


def model_elastic_round(buggy: bool = False):
    """Endpoint A dies mid-round; the trainer must forget its conns,
    wait for a FRESH stable view, and replay the whole round against
    the new placement.  buggy=True replays against the view it already
    has (the pre-elastic_round shape): the round retries into the dead
    endpoint forever — bounded here by an attempt cap, surfacing as a
    round that never completes."""

    def run():
        cond = threading.Condition()
        state = {
            "view": {"epoch": 1, "place": {"g0": "A", "g1": "B"}},
            "endpoints": {"A": {"dead": False, "applied": []},
                          "B": {"dead": False, "applied": []}},
            "round_ok": False,
            "attempts": 0,
        }

        def send(ep, grad):
            e = state["endpoints"][ep]
            if e["dead"]:
                raise ConnectionError(f"{ep} is dead")
            e["applied"].append(grad)

        def killer():
            with cond:
                state["endpoints"]["A"]["dead"] = True
                cond.notify_all()

        def controller():
            # publishes the post-death view once A is observed dead
            with cond:
                while not state["endpoints"]["A"]["dead"]:
                    cond.wait()
                state["view"] = {"epoch": 2,
                                 "place": {"g0": "B", "g1": "B"}}
                cond.notify_all()

        def trainer():
            with cond:
                view = state["view"]
            for _ in range(6):              # attempt cap
                state["attempts"] += 1
                try:
                    for grad, ep in sorted(view["place"].items()):
                        send(ep, grad)
                    state["round_ok"] = True
                    return
                except ConnectionError:
                    if buggy:
                        continue            # replay the STALE view
                    with cond:
                        epoch = view["epoch"]
                        while state["view"]["epoch"] <= epoch:
                            cond.wait()
                        view = state["view"]

        ts = [threading.Thread(target=killer),
              threading.Thread(target=controller),
              threading.Thread(target=trainer)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return state

    return run


def invariant_elastic_round(state):
    assert state["round_ok"], \
        f"round never completed ({state['attempts']} attempts)"
    applied = (state["endpoints"]["A"]["applied"]
               + state["endpoints"]["B"]["applied"])
    # at-least-once: every grad applied somewhere (replays may double)
    for g in ("g0", "g1"):
        assert g in applied, f"{g} never applied: {applied}"


# ---------------------------------------------------------------------------
# GenerationServer admit/finish/hot-swap over the REAL PagedKVCache
# ---------------------------------------------------------------------------


def model_generation_admit_finish_swap(buggy: bool = False):
    """The serving scheduler's slot protocol against the production
    KV-cache accounting: FIFO admission gated on free blocks, per-tick
    cursor advance with prefix commit, release on finish, and a hot
    swap (pause admission -> drain -> install -> resume) racing the
    whole thing.  buggy=True drops the release() for a sequence whose
    finish lands while the swap is draining — the PR 8 eviction-leak
    shape; the pool never returns to full."""

    def run():
        from ..serving.kv_cache import KVPoolExhausted, PagedKVCache

        cache = PagedKVCache(6, block_size=2, max_blocks_per_seq=4,
                             prefix_cache=True,
                             server_label="schedmodel")
        cond = threading.Condition()
        state = {
            "cache": cache,
            # (owner-id, prompt tokens, total positions needed)
            "queue": [("r1", [1, 2, 3, 4], 6),
                      ("r2", [1, 2, 3, 4], 6),   # shares r1's prefix
                      ("r3", [7, 8], 4)],
            "active": {},        # owner -> cursor/need
            "finished": [],
            "swap": {"pending": False, "installed": 0},
            "stop": False,
        }

        def scheduler():
            while True:
                with cond:
                    while True:
                        if (not state["queue"]
                                and not state["active"]
                                and not state["swap"]["pending"]
                                and state["swap"]["installed"]):
                            # drained AND the announced swap landed
                            # (exiting before the swapper even set
                            # `pending` would strand it — the checker
                            # found exactly that in an earlier draft)
                            return
                        # hot swap: admission paused; drain actives
                        if state["swap"]["pending"] \
                                and not state["active"]:
                            cache.flush_prefix()
                            state["swap"]["installed"] += 1
                            state["swap"]["pending"] = False
                            cond.notify_all()
                            continue   # re-check exit from the top
                        admitted = False
                        while (state["queue"]
                               and not state["swap"]["pending"]
                               and len(state["active"]) < 2):
                            owner, prompt, need = state["queue"][0]
                            if not cache.can_admit(
                                    need, prompt_tokens=prompt):
                                break
                            try:
                                table, cached = cache.allocate_prefix(
                                    owner, need, prompt_tokens=prompt)
                            except KVPoolExhausted:
                                break
                            state["queue"].pop(0)
                            state["active"][owner] = {
                                "cursor": cached, "need": need,
                                "prompt": prompt}
                            admitted = True
                        if state["active"] or admitted:
                            break
                        # queued work we cannot admit yet (or a swap
                        # waiting on actives): let other threads move
                        cond.wait()
                # one decode tick outside the admission lock (the real
                # scheduler dispatches the jitted step here)
                with cond:
                    done = []
                    for owner, seq in state["active"].items():
                        seq["cursor"] += 1
                        cache.commit_prefix(owner, seq["cursor"])
                        if seq["cursor"] >= seq["need"]:
                            done.append(owner)
                    for owner in done:
                        state["active"].pop(owner)
                        leak = (buggy and state["swap"]["pending"])
                        if not leak:
                            cache.release(owner)
                        state["finished"].append(owner)
                    cond.notify_all()

        def swapper():
            with cond:
                state["swap"]["pending"] = True
                cond.notify_all()
                while state["swap"]["pending"]:
                    cond.wait()

        ts = [threading.Thread(target=scheduler),
              threading.Thread(target=swapper)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return state

    return run


def invariant_generation_admit_finish_swap(state):
    cache = state["cache"]
    assert sorted(state["finished"]) == ["r1", "r2", "r3"], \
        state["finished"]
    assert state["swap"]["installed"] == 1, state["swap"]
    # KV-block refcount balance: after drain + flush, the pool is
    # whole again and no live references remain
    cache.flush_prefix()
    assert cache.free_blocks == cache.num_blocks, (
        f"leaked KV blocks: {cache.num_blocks - cache.free_blocks} "
        "still resident after drain")
    assert not cache._ref, f"dangling refcounts: {cache._ref}"
    assert not cache._owned, f"dangling owners: {list(cache._owned)}"
    cache.close()


# ---------------------------------------------------------------------------
# CommPool.send_round per-endpoint ordering (parallel/comm.py)
# ---------------------------------------------------------------------------


def model_comm_send_round(buggy: bool = False):
    """Two caller threads run a fused round over the same two
    endpoints.  The pool's contract: each endpoint's wire sees one
    round's frame chain (sends -> barrier -> get) CONTIGUOUSLY,
    because only that endpoint's single worker touches its socket.
    buggy=True has callers write the shared socket directly — an
    interleaving the checker must find."""

    def run():
        wires: Dict[str, list] = {"ep_a": [], "ep_b": []}
        workers: Dict[str, queue.Queue] = {}
        threads = []
        stop = object()

        def worker(ep, q):
            while True:
                task = q.get()
                if task is stop:
                    return
                fn, done = task
                fn()
                done.set()

        for ep in wires:
            q = queue.Queue()
            workers[ep] = q
            t = threading.Thread(target=worker, args=(ep, q))
            t.start()
            threads.append(t)

        def frame_chain(caller, ep):
            # the per-endpoint chain; a yield point between frames is
            # implicit in the queue/lock operations around this, and
            # the buggy path interleaves exactly there
            from . import schedcheck

            for frame in ("send", "barrier", "get"):
                wires[ep].append((caller, frame))
                schedcheck.yield_point("wire-frame")

        def send_round(caller):
            if buggy:
                for ep in sorted(wires):
                    frame_chain(caller, ep)
                return
            dones = []
            for ep in sorted(wires):
                done = threading.Event()
                workers[ep].put(
                    (lambda c=caller, e=ep: frame_chain(c, e), done))
                dones.append(done)
            for done in dones:
                done.wait()

        callers = [threading.Thread(target=send_round, args=(c,))
                   for c in ("t1", "t2")]
        for t in callers:
            t.start()
        for t in callers:
            t.join()
        for q in workers.values():
            q.put(stop)
        for t in threads:
            t.join()
        return wires

    return run


def invariant_comm_send_round(wires):
    for ep, frames in wires.items():
        assert len(frames) == 6, (ep, frames)
        # contiguous per caller: caller runs of exactly 3
        callers = [c for c, _ in frames]
        assert callers[0] == callers[1] == callers[2] and \
            callers[3] == callers[4] == callers[5], (
                f"{ep}: rounds interleaved on one socket: {frames}")
        chain = [f for _, f in frames]
        assert chain == ["send", "barrier", "get"] * 2, (ep, frames)


PROTOCOLS: Dict[str, Tuple[Callable[..., Callable], Callable]] = {
    "fence_migrate_commit": (model_fence_migrate_commit,
                             invariant_fence_migrate_commit),
    "elastic_round": (model_elastic_round, invariant_elastic_round),
    "generation_admit_finish_swap": (
        model_generation_admit_finish_swap,
        invariant_generation_admit_finish_swap),
    "comm_send_round": (model_comm_send_round,
                        invariant_comm_send_round),
}
