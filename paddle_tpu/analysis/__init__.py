"""paddle_tpu.analysis — build-time static analysis of the Program IR.

A pass-based verifier over Program/Block/Operator descs: catches bad
graphs in milliseconds at build time instead of minutes into an XLA
trace.  The Fluid architecture compiles the whole program before
anything runs (framework.proto OpDesc/VarDesc, compile-time InferShape);
this package is the reproduction's analogue of that compile-time
checking layer, upgraded from scattered per-op asserts to a real
analyzer with structured diagnostics.

Entry points:
  * `Program.verify(level=...)` (core/framework.py) — the user surface;
  * `verify_program(program, ...)` — the functional driver;
  * `preflight(program, ...)` — the Executor/ParallelExecutor hook,
    gated by the `verify` flag (env `PADDLE_TPU_VERIFY=off|warn|error`)
    and cached per program version so steady-state training loops pay
    nothing;
  * `register_pass` — extend the pipeline with project-specific
    invariants (docs/analysis.md shows a worked example).
"""
from __future__ import annotations

import warnings
import weakref
from typing import Iterable, List, Optional

from .diagnostics import (  # noqa: F401
    Diagnostic,
    ProgramVerificationError,
    SEVERITIES,
    format_diagnostics,
    max_severity,
    severity_rank,
)
from .registry import (  # noqa: F401
    AnalysisPass,
    PassContext,
    get_pass,
    register_pass,
    registered_passes,
    verify_program,
)
from . import passes as _builtin_passes  # noqa: F401  (registers built-ins)
from . import cost_model  # noqa: F401  (registers cost/comm passes)
from . import concurrency  # noqa: F401  (AST concurrency analyzer)
from . import schedcheck  # noqa: F401  (deterministic-schedule checker)
from .cost_model import (  # noqa: F401
    CommEstimate,
    OpCost,
    ProgramCostEstimate,
    analyze_generation_spec,
    check_budget,
    estimate_comm,
    estimate_op,
    estimate_peak_hbm,
    estimate_program,
    ridge_point,
    serving_kernel_cost,
)

__all__ = [
    "Diagnostic",
    "ProgramVerificationError",
    "SEVERITIES",
    "format_diagnostics",
    "max_severity",
    "register_pass",
    "registered_passes",
    "get_pass",
    "verify_program",
    "preflight",
    "PassContext",
    "AnalysisPass",
    "OpCost",
    "ProgramCostEstimate",
    "CommEstimate",
    "estimate_op",
    "estimate_program",
    "estimate_peak_hbm",
    "estimate_comm",
    "ridge_point",
    "analyze_generation_spec",
    "serving_kernel_cost",
    "check_budget",
    "concurrency",
    "schedcheck",
]


# program -> (version, mode) already verified; weak keys so a dropped
# Program releases its entry.  One program is re-verified only when it
# mutates (bump_version) or the verify mode changes.
_preflight_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _verify_mode() -> str:
    from ..core.flags import get_flag

    mode = str(get_flag("verify") or "off").lower()
    if mode not in ("off", "warn", "error"):
        raise ValueError(
            f"PADDLE_TPU_VERIFY must be off|warn|error, got {mode!r}")
    return mode


def preflight(
    program,
    feed_names: Optional[Iterable[str]] = None,
    fetch_names: Optional[Iterable[str]] = None,
) -> List[Diagnostic]:
    """Flag-gated verification before an executor runs `program`.

    `PADDLE_TPU_VERIFY=off` (default): no-op.  `warn`: run the analyzer
    and emit one RuntimeWarning per error/warning diagnostic.  `error`:
    additionally raise ProgramVerificationError when any error-severity
    diagnostic exists.  Results are cached per (program, version, mode):
    a training loop re-running one stable program verifies exactly once.

    Empty feed/fetch containers are treated as "context unknown", not
    "known empty": a warm-up `exe.run(prog)` with no fetch_list must
    not upgrade dead-op findings to warnings for the whole cached
    program.
    """
    mode = _verify_mode()
    if mode == "off":
        return []
    feed_names = feed_names or None
    fetch_names = fetch_names or None
    try:
        cached = _preflight_cache.get(program)
    except TypeError:  # unhashable/weakref-less program stand-in
        cached = None
    if cached is not None and cached == (program._version, mode):
        return []
    diagnostics = verify_program(program, feed_names=feed_names,
                                 fetch_names=fetch_names)
    errors = [d for d in diagnostics if d.severity == "error"]
    notable = [d for d in diagnostics if d.severity != "info"]
    if mode == "error" and errors:
        raise ProgramVerificationError(errors)
    if notable:
        warnings.warn(
            "program verification found issues:\n"
            + format_diagnostics(notable),
            RuntimeWarning, stacklevel=3)
    try:
        _preflight_cache[program] = (program._version, mode)
    except TypeError:
        pass
    return diagnostics
