"""Built-in analysis passes over the Program IR.

Each pass checks one class of build-time invariant that used to surface
(if at all) as a cryptic runtime failure deep inside a JAX trace.  Pass
ids are stable API — tests pin them, `verify(passes=[...])` filters by
them, and docs/analysis.md catalogs them.

Severity conventions (see docs/analysis.md):
  * error   — the program cannot execute correctly (dangling name,
    invalid sub-block index, malformed distributed attrs);
  * warning — legal to execute but almost certainly a bug (undeclared
    in-place clobber with a later reader, dtype conflict on a shared
    var, non-duplicable slot bound to several vars);
  * info    — hygiene / performance observations (dead ops without
    fetch context, data-dependent -1 dims that trigger recompiles).
"""
from __future__ import annotations

import re
from typing import Dict, List, Set

from ..core import shape_inference
from ..core.framework import EMPTY_VAR_NAMES, GRAD_SUFFIX, Parameter
from .registry import register_pass

_GRAD = "_grad"


def _lookup_var(block, name):
    try:
        return block.var(name)
    except KeyError:
        return None


def _safe_parent(program, block):
    """block.parent, but tolerant of corrupt parent_idx (a deserialized
    bad program must produce diagnostics, not an IndexError inside the
    verifier — the control-flow pass reports the broken link itself)."""
    if not 0 <= block.parent_idx < len(program.blocks):
        return None
    return program.blocks[block.parent_idx]


def _fwd_info_of_grad(ctx, op):
    """OpInfo of the forward op for a '<fwd>_grad' op desc, else None."""
    if not op.type.endswith(_GRAD):
        return None
    from ..core import registry as op_registry

    try:
        return op_registry.get_op_info(op.type[: -len(_GRAD)])
    except KeyError:
        return None


# ---------------------------------------------------------------------------
# 1. def-before-use / dangling inputs
# ---------------------------------------------------------------------------


@register_pass("def-before-use", order=10)
def check_def_before_use(ctx):
    """Every op input must resolve to a variable in the op's block or an
    ancestor block (`@EMPTY@` / '' sentinels excepted).  In the global
    block, additionally warn when a value is read before the op that
    first produces it (feed vars — never produced — are exempt, as are
    loop-state vars also written from sub-blocks)."""
    # names written from inside any sub-block: loop/branch state whose
    # global-block read order is not a straight-line data dependency
    sub_written: Set[str] = set()
    for block in ctx.program.blocks[1:]:
        for op in block.ops:
            sub_written.update(op.output_names())

    for block in ctx.program.blocks:
        first_write: Dict[str, int] = {}
        for idx, op in enumerate(block.ops):
            for n in op.output_names():
                first_write.setdefault(n, idx)
        for idx, op in enumerate(block.ops):
            for n in op.input_names():
                if n in EMPTY_VAR_NAMES:
                    continue
                if not ctx.resolvable(block, n):
                    yield ctx.diag(
                        "error",
                        f"input {n!r} of op {op.type!r} does not resolve "
                        "to any variable in this block or its ancestors",
                        block, idx, op,
                        hint="the var was never created (renamed grad? "
                             "pruned producer?) — create it or fix the "
                             "op's input name",
                    )
                    continue
                if block.idx != 0:
                    continue  # ordering only checked on the global block
                w = first_write.get(n)
                if (w is not None and w > idx and n not in sub_written
                        and (ctx.feed_names is None
                             or n not in ctx.feed_names)):
                    v = _lookup_var(block, n)
                    if v is not None and v.persistable:
                        continue  # scope-carried state (params, counters)
                    yield ctx.diag(
                        "warning",
                        f"op {op.type!r} reads {n!r} at position {idx} "
                        f"but its first producer runs later (op {w})",
                        block, idx, op,
                        hint="reorder the ops, or feed the value "
                             "explicitly",
                    )


# ---------------------------------------------------------------------------
# 2. slot arity + duplicability vs registry OpInfo
# ---------------------------------------------------------------------------


@register_pass("op-arity", order=20)
def check_op_arity(ctx):
    """Every Operator's slots must match its registry OpInfo: no unknown
    slots, and only slots declared duplicable may bind several vars.
    Unregistered op types are errors (the executor cannot lower them)."""
    for block, idx, op in ctx.iter_ops():
        info = ctx.op_info(op)
        if info is None:
            yield ctx.diag(
                "error",
                f"op type {op.type!r} is not registered — it has no "
                "lowering and will raise at execution",
                block, idx, op,
                hint="register it with core.registry.register_op, or "
                     "fix the op type",
            )
            continue
        fwd = _fwd_info_of_grad(ctx, op)
        if fwd is not None:
            # generic grad desc carries fwd inputs + fwd outputs +
            # '<out>@GRAD' cotangents; outputs are '<in>@GRAD'
            in_ok = (set(fwd.inputs) | set(fwd.outputs)
                     | {s + GRAD_SUFFIX for s in fwd.outputs})
            out_ok = {s + GRAD_SUFFIX for s in fwd.inputs}
            dup_in = (set(fwd.dup_inputs) | set(fwd.dup_outputs)
                      | {s + GRAD_SUFFIX for s in fwd.dup_outputs})
            dup_out = {s + GRAD_SUFFIX for s in fwd.dup_inputs}
            if info.type == op.type:  # explicitly registered grad op
                in_ok |= set(info.inputs)
                out_ok |= set(info.outputs)
                dup_in |= set(info.dup_inputs)
                dup_out |= set(info.dup_outputs)
        elif info.type != op.type:
            continue  # grad of an unregistered fwd: arity unknowable
        else:
            in_ok, out_ok = set(info.inputs), set(info.outputs)
            dup_in, dup_out = set(info.dup_inputs), set(info.dup_outputs)
        for slot in op.inputs:
            if slot not in in_ok:
                yield ctx.diag(
                    "error",
                    f"op {op.type!r} binds undeclared input slot "
                    f"{slot!r} (declared: {sorted(in_ok)})",
                    block, idx, op,
                    hint="declare the slot in the register_op call or "
                         "drop it from the op desc",
                )
            elif len(op.inputs[slot]) > 1 and slot not in dup_in:
                yield ctx.diag(
                    "warning",
                    f"op {op.type!r} binds {len(op.inputs[slot])} vars "
                    f"to non-duplicable input slot {slot!r}",
                    block, idx, op,
                    hint="mark the slot with dup_inputs=(...) in "
                         "register_op if multi-var is intended",
                )
        for slot in op.outputs:
            if slot not in out_ok:
                yield ctx.diag(
                    "error",
                    f"op {op.type!r} binds undeclared output slot "
                    f"{slot!r} (declared: {sorted(out_ok)})",
                    block, idx, op,
                    hint="declare the slot in the register_op call or "
                         "drop it from the op desc",
                )
            elif len(op.outputs[slot]) > 1 and slot not in dup_out:
                yield ctx.diag(
                    "warning",
                    f"op {op.type!r} binds {len(op.outputs[slot])} vars "
                    f"to non-duplicable output slot {slot!r}",
                    block, idx, op,
                    hint="mark the slot with dup_outputs=(...) in "
                         "register_op if multi-var is intended",
                )


# ---------------------------------------------------------------------------
# 3. full shape/dtype propagation
# ---------------------------------------------------------------------------


@register_pass("shape-inference", order=30)
def check_shape_inference(ctx):
    """Re-run build-time shape inference over every op of every block and
    report what the old code silently dropped: ops whose default
    inference fails (so their output shapes stay unknown), inputs with
    no declared shape, and dtype conflicts between writers of a shared
    var.  Also flags data-dependent (-1) non-leading dims — the classic
    cause of hot-loop recompiles (docs/performance.md).

    Verification must not mutate the program: declared shapes/dtypes
    are snapshotted first and restored afterwards (re-inference under
    different trace-time flags, e.g. amp_bf16, would otherwise rewrite
    them)."""
    snapshot = [
        (v, v.shape, v.dtype)
        for block in ctx.program.blocks for v in block.vars.values()
    ]
    try:
        yield from _run_shape_inference(ctx)
    finally:
        for v, shape, dtype in snapshot:
            v.shape, v.dtype = shape, dtype


def _run_shape_inference(ctx):
    for block, idx, op in ctx.iter_ops():
        info = ctx.op_info(op)
        if info is None:
            continue  # op-arity reports unregistered types
        if info.host:
            continue  # host ops (save/load/send/print) do IO, not shapes
        reports: List = []

        def report(kind, **kw):
            reports.append((kind, kw))

        try:
            if info.infer_shape is not None and info.type == op.type:
                info.infer_shape(op, block)
            elif op.type.endswith(_GRAD):
                shape_inference.infer_grad_shapes(op, block)
            else:
                shape_inference.default_infer_shape(op, block,
                                                    report=report)
        except KeyError:
            continue  # dangling input name: def-before-use reports it
        except Exception as e:
            yield ctx.diag(
                "warning",
                f"explicit infer_shape for {op.type!r} raised "
                f"{type(e).__name__}: {e}",
                block, idx, op,
            )
            continue
        for kind, kw in reports:
            if kind == "infer-fail":
                yield ctx.diag(
                    "warning",
                    f"shape inference failed for op {op.type!r}: "
                    f"{type(kw['error']).__name__}: {kw['error']}",
                    block, idx, op,
                    hint="register an explicit inference fn via "
                         "core.registry.register_infer_shape"
                         f"({op.type!r})",
                )
            elif kind == "dtype-mismatch":
                yield ctx.diag(
                    "warning",
                    f"op {op.type!r} writes {kw['name']!r} as "
                    f"{kw['inferred']} but the var is already declared "
                    f"{kw['declared']} by an earlier writer",
                    block, idx, op,
                    hint="two ops share one output name with "
                         "conflicting dtypes — rename one output or "
                         "insert a cast",
                )
            elif kind == "unknown-input":
                yield ctx.diag(
                    "info",
                    f"op {op.type!r}: input {kw['name']!r} has no "
                    "declared shape/dtype, so output shapes were not "
                    "inferred",
                    block, idx, op,
                )

    # -1 sentinels beyond the leading (batch) dim: every distinct value
    # of such a dim is a fresh executable (recompile on the hot path)
    for block in ctx.program.blocks:
        flagged = [
            name for name, v in block.vars.items()
            if v.shape is not None and any(d < 0 for d in v.shape[1:])
        ]
        if flagged:
            show = ", ".join(sorted(flagged)[:5])
            more = len(flagged) - min(5, len(flagged))
            yield ctx.diag(
                "info",
                f"{len(flagged)} var(s) have data-dependent (-1) "
                f"non-leading dims ({show}"
                + (f", +{more} more" if more else "") + ")",
                block,
                hint="dynamic dims recompile per distinct size — "
                     "bucket/pad lengths (docs/performance.md, "
                     "'recompiles')",
            )


# ---------------------------------------------------------------------------
# 4. dead ops (outputs never consumed)
# ---------------------------------------------------------------------------


@register_pass("dead-op", order=40)
def check_dead_ops(ctx):
    """Flag ops whose outputs are never read by any later op, are not
    persistable/parameters, and (when the fetch list is known) are not
    fetched.  Host/side-effect ops and control-flow ops are exempt.
    Without fetch context the finding is informational — a leaf output
    may well be the value the user fetches."""
    read_anywhere: Set[str] = set()
    for _, _, op in ctx.iter_ops():
        read_anywhere.update(op.input_names())
    if ctx.fetch_names:
        read_anywhere |= ctx.fetch_names

    for block, idx, op in ctx.iter_ops():
        info = ctx.op_info(op)
        if info is None or info.host:
            continue
        if any(a.endswith("block") for a in op.attrs):
            continue  # control flow: sub-block dataflow is indirect
        outs = [n for n in op.output_names() if n not in EMPTY_VAR_NAMES]
        if not outs:
            continue  # pure side-effect op (send barrier, cond assert)
        live = False
        for n in outs:
            if n in read_anywhere:
                live = True
                break
            v = _lookup_var(block, n)
            if v is not None and (v.persistable or isinstance(v, Parameter)):
                live = True
                break
        if not live:
            yield ctx.diag(
                "warning" if ctx.fetch_names is not None else "info",
                f"op {op.type!r} is dead: outputs {outs} are never "
                "read, fetched, or persisted",
                block, idx, op,
                hint="remove the op, or fetch/persist its result",
            )


# ---------------------------------------------------------------------------
# 5. variable shadowing across nested blocks
# ---------------------------------------------------------------------------


@register_pass("var-shadowing", order=50)
def check_var_shadowing(ctx):
    """A var name redeclared in a nested block with a DIFFERENT
    shape/dtype than an ancestor's var of the same name: ancestor-chain
    lookup (Block.var) silently resolves to whichever is nearer, so the
    two declarations are one runtime slot with two conflicting types."""
    for block in ctx.program.blocks[1:]:
        for name, v in block.vars.items():
            b = _safe_parent(ctx.program, block)
            seen = {block.idx}
            while b is not None and b.idx not in seen:
                seen.add(b.idx)
                other = b.vars.get(name)
                if other is None:
                    b = _safe_parent(ctx.program, b)
                    continue
                mismatch = []
                if (v.shape is not None and other.shape is not None
                        and tuple(v.shape) != tuple(other.shape)):
                    mismatch.append(
                        f"shape {list(v.shape)} vs "
                        f"{list(other.shape)}")
                if (v.dtype is not None and other.dtype is not None
                        and v.dtype != other.dtype):
                    mismatch.append(f"dtype {v.dtype} vs {other.dtype}")
                if mismatch:
                    yield ctx.diag(
                        "warning",
                        f"var {name!r} in block {block.idx} shadows "
                        f"block {b.idx}'s var with mismatched "
                        + " and ".join(mismatch),
                        block,
                        hint="rename the inner var (unique_name) or "
                             "align the declarations",
                    )
                break  # nearest ancestor declaration wins the lookup


# ---------------------------------------------------------------------------
# 6. control-flow integrity
# ---------------------------------------------------------------------------


def _block_refs(op):
    """(attr_name, block_idx) for every sub-block reference on `op`."""
    refs = []
    for a, v in op.attrs.items():
        if isinstance(v, dict) and "__block__" in v:
            refs.append((a, v["__block__"]))
        elif a.endswith("block") and isinstance(v, int):
            refs.append((a, v))
    return refs


@register_pass("control-flow", order=60)
def check_control_flow(ctx):
    """Sub-block references must index real blocks whose parent chain
    reaches the op's own block (captured vars resolve along it); block
    parent links must be valid and acyclic; a '<t>_grad' op carrying a
    grad sub-block needs its paired forward '<t>' op in the program."""
    n = len(ctx.program.blocks)
    # parent link sanity first: a broken chain breaks every other check
    for block in ctx.program.blocks[1:]:
        if not 0 <= block.parent_idx < n:
            yield ctx.diag(
                "error",
                f"block {block.idx} has invalid parent_idx "
                f"{block.parent_idx} (program has {n} blocks)",
                block,
            )
            continue
        seen = {block.idx}
        b = block
        while 0 <= b.parent_idx < n:
            if b.parent_idx in seen:
                yield ctx.diag(
                    "error",
                    f"block {block.idx}'s parent chain cycles at block "
                    f"{b.parent_idx}",
                    block,
                )
                break
            seen.add(b.parent_idx)
            b = ctx.program.blocks[b.parent_idx]
            # an ancestor's own bad parent_idx is reported when the
            # outer loop reaches that block; stop walking here

    referenced: Set[int] = set()
    fwd_types = {op.type for _, _, op in ctx.iter_ops()
                 if not op.type.endswith(_GRAD)}
    for block, idx, op in ctx.iter_ops():
        for attr, tidx in _block_refs(op):
            if not isinstance(tidx, int) or not 0 <= tidx < n:
                yield ctx.diag(
                    "error",
                    f"op {op.type!r} attr {attr!r} references block "
                    f"{tidx!r}, but the program has {n} blocks",
                    block, idx, op,
                    hint="sub-block indices break when blocks are "
                         "copied between programs — rebuild via "
                         "Program.from_dict/clone",
                )
                continue
            referenced.add(tidx)
            if tidx == block.idx:
                yield ctx.diag(
                    "error",
                    f"op {op.type!r} attr {attr!r} references its own "
                    f"block {tidx} as a sub-block",
                    block, idx, op,
                )
                continue
            # captured names resolve through the sub-block's parent
            # chain — that chain must pass through the op's block
            sub = ctx.program.blocks[tidx]
            chain = set()
            b = sub
            while b is not None and b.idx not in chain:
                chain.add(b.idx)
                b = (ctx.program.blocks[b.parent_idx]
                     if 0 <= b.parent_idx < n else None)
            if block.idx not in chain:
                yield ctx.diag(
                    "warning",
                    f"sub-block {tidx} of op {op.type!r} does not have "
                    f"block {block.idx} on its parent chain — captured "
                    "vars will not resolve to this block's scope",
                    block, idx, op,
                )
        if op.type.endswith(_GRAD) and _block_refs(op):
            fwd_type = op.type[: -len(_GRAD)]
            if fwd_type not in fwd_types:
                yield ctx.diag(
                    "warning",
                    f"grad op {op.type!r} carries a grad sub-block but "
                    f"no forward {fwd_type!r} op exists in the program",
                    block, idx, op,
                )
    for block in ctx.program.blocks[1:]:
        if block.idx not in referenced:
            yield ctx.diag(
                "info",
                f"block {block.idx} is not referenced by any op's "
                "sub-block attr (orphaned by a rewrite?)",
                block,
            )


# ---------------------------------------------------------------------------
# 7. distributed lint
# ---------------------------------------------------------------------------

_ENDPOINT_RE = re.compile(r"^[\w.\-]+:\d+$")


def _effective_attrs(ctx, op):
    """Attrs as dispatch sees them: registered defaults overlaid by the
    op desc ({**info.attrs, **op.attrs}, core/execution.run_op) — a lint
    on raw op.attrs would flag ops that legally rely on defaults."""
    info = ctx.op_info(op)
    if info is not None and info.type == op.type:
        return {**info.attrs, **op.attrs}
    return op.attrs


def _check_endpoint(ctx, block, idx, op, attr, value):
    if not isinstance(value, str) or not _ENDPOINT_RE.match(value):
        return ctx.diag(
            "error",
            f"op {op.type!r} attr {attr!r} is {value!r}, not a "
            "'host:port' endpoint",
            block, idx, op,
            hint="endpoints come from the transpiler config "
                 "(trainer/pserver endpoint lists)",
        )
    return None


@register_pass("distributed-lint", order=70)
def check_distributed(ctx):
    """Distributed attrs checked before anything hits the network:
    send/recv/listen_and_serv endpoints well-formed and consistently
    paired, epmap arity matching the var list, pipeline_stage
    annotations monotone and contiguous per block, parallel_do ops
    agreeing on the participant count."""
    listen_eps: Set[str] = set()
    send_eps: Set[str] = set()
    num_places_seen: Dict[int, int] = {}  # num_places -> first op idx
    bucketed_sends: List[int] = []    # op idx: per-var epmap present
    unbucketed_sends: List[int] = []  # op idx: endpoints only

    for block, idx, op in ctx.iter_ops():
        attrs = _effective_attrs(ctx, op)
        if op.type == "send":
            endpoints = list(attrs.get("endpoints") or ())
            epmap = list(attrs.get("epmap") or ())
            out_epmap = list(attrs.get("out_epmap") or ())
            if not endpoints and not epmap:
                yield ctx.diag(
                    "error",
                    "send op has neither 'endpoints' nor 'epmap' — "
                    "there is nowhere to send to",
                    block, idx, op,
                )
                continue
            (bucketed_sends if epmap else unbucketed_sends).append(idx)
            n_in = len(op.input("X"))
            if epmap and len(epmap) != n_in:
                yield ctx.diag(
                    "error",
                    f"send op epmap has {len(epmap)} endpoints for "
                    f"{n_in} input vars — per-var mapping must match "
                    "1:1",
                    block, idx, op,
                )
            n_out = len(op.output("Out"))
            if out_epmap and len(out_epmap) != n_out:
                yield ctx.diag(
                    "error",
                    f"send op out_epmap has {len(out_epmap)} endpoints "
                    f"for {n_out} output vars — per-var mapping must "
                    "match 1:1",
                    block, idx, op,
                )
            for ep in endpoints + epmap + out_epmap:
                d = _check_endpoint(ctx, block, idx, op, "endpoints", ep)
                if d is not None:
                    yield d
                else:
                    send_eps.add(ep)
            if endpoints and (epmap or out_epmap):
                stray = sorted((set(epmap) | set(out_epmap)) -
                               set(endpoints))
                if stray:
                    yield ctx.diag(
                        "warning",
                        f"send op epmap routes to {stray} which are not "
                        "in its 'endpoints' list",
                        block, idx, op,
                    )
        elif op.type == "recv":
            ep = attrs.get("endpoint", "")
            if not ep:
                yield ctx.diag(
                    "error",
                    "recv op has an empty 'endpoint' attr",
                    block, idx, op,
                )
            else:
                d = _check_endpoint(ctx, block, idx, op, "endpoint", ep)
                if d is not None:
                    yield d
                else:
                    send_eps.add(ep)
        elif op.type == "listen_and_serv":
            ep = attrs.get("endpoint", "")
            d = _check_endpoint(ctx, block, idx, op, "endpoint", ep)
            if d is not None:
                yield d
            else:
                listen_eps.add(ep)
        elif op.type.startswith("c_"):
            ring = attrs.get("ring_id")
            if not isinstance(ring, str) or not ring:
                yield ctx.diag(
                    "error",
                    f"collective op {op.type!r} has invalid ring_id "
                    f"{ring!r} — expected a mesh axis name",
                    block, idx, op,
                )
        elif op.type == "parallel_do":
            np_ = int(attrs.get("num_places", 0) or 0)
            if np_:
                num_places_seen.setdefault(np_, idx)

    if bucketed_sends and unbucketed_sends:
        yield ctx.diag(
            "warning",
            "program mixes bucketed send ops (per-var epmap, ops "
            f"{bucketed_sends}) with unbucketed ones (endpoints only, "
            f"ops {unbucketed_sends}) — rounds behind the unbucketed "
            "ops cannot fuse transfers or overlap endpoints",
            ctx.program.blocks[0],
            hint="give every send op a per-var epmap (+ out_epmap for "
                 "the pulls) — the transpiler emits one fused send per "
                 "program in exactly that shape",
        )
    if len(num_places_seen) > 1:
        yield ctx.diag(
            "warning",
            "parallel_do ops disagree on participant count "
            f"(num_places values: {sorted(num_places_seen)})",
            ctx.program.blocks[0],
            hint="all replicas of one program must shard over the same "
                 "device count",
        )
    if listen_eps and send_eps:
        unmatched = sorted(send_eps - listen_eps)
        if unmatched:
            yield ctx.diag(
                "warning",
                f"send/recv endpoints {unmatched} have no "
                "listen_and_serv peer in this program",
                ctx.program.blocks[0],
                hint="trainer and pserver programs are usually "
                     "separate; ignore if the server runs elsewhere",
            )

    # pipeline stages: monotone non-decreasing and contiguous per block.
    # Grad ops inherit their forward op's stage and run in REVERSE stage
    # order by construction (backward.py copies attrs) — only the
    # forward trunk must be monotone.
    for block in ctx.program.blocks:
        staged = [(i, int(op.attrs["pipeline_stage"]))
                  for i, op in enumerate(block.ops)
                  if "pipeline_stage" in op.attrs
                  and not op.type.endswith(_GRAD)]
        if not staged:
            continue
        prev_i, prev_s = staged[0]
        for i, s in staged[1:]:
            if s < prev_s:
                op = block.ops[i]
                yield ctx.diag(
                    "warning",
                    f"pipeline_stage decreases from {prev_s} (op "
                    f"{prev_i}) to {s} (op {i}) — the GPipe trunk "
                    "expects stages in execution order",
                    block, i, op,
                    hint="build staged layers in stage order under "
                         "fluid.pipeline_stage(i)",
                )
                break
            prev_i, prev_s = i, s
        stages = sorted({s for _, s in staged})
        if stages and (stages[0] != 0
                       or stages != list(range(len(stages)))):
            yield ctx.diag(
                "info",
                f"pipeline stages in block {block.idx} are "
                f"{stages} — not a contiguous 0..{len(stages) - 1} "
                "range",
                block,
                hint="PipelineExecutor maps stages onto the 'pp' mesh "
                     "axis positionally",
            )


# ---------------------------------------------------------------------------
# 8. in-place aliasing hazards
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# 7b. collective safety (deadlock class of the mesh lowerings)
# ---------------------------------------------------------------------------

# the pipeline schedule's own hop primitive — the one legitimate user of
# the stage axis's ring from inside a staged region
_PIPELINE_HOPS = ("c_ppermute",)
_STAGE_AXIS = "pp"
# control-flow ops whose sub-block executes on a data-dependent subset
# of ranks (branch predicates can differ per rank)
_BRANCH_OPS = ("cond", "conditional_block")
_LOOP_OPS = ("while", "recurrent", "dynamic_rnn")


@register_pass("collective-safety", order=73)
def check_collective_safety(ctx):
    """Static deadlock analysis of the program's collective structure —
    the class of bug the ep x dp x tp composition hits at RUNTIME as a
    silent all-rank hang, caught here from the op descs.

    Rank model: `ring_id` names one communicator spanning every rank
    that references it; ops carrying a `pipeline_stage` attr are issued
    only by that stage's ranks, unstaged ops by all ranks.  Under SPMD
    every participating rank must issue an IDENTICAL sequence of
    collectives per ring, so the pass errors on:

      * cross-rank ordering mismatch — two stages issue the same ring's
        collectives in different orders (every rank blocks inside a
        different collective; none completes);
      * pipeline-stage collective imbalance — stages disagree on HOW
        MANY collectives they issue on one ring (some ranks wait at a
        collective their peers never reach);
      * ring_id reuse across overlapping groups — a staged collective
        (other than the schedule's own `c_ppermute` hops) over the
        stage axis's ring: the per-stage subgroup overlaps the
        schedule's full-axis group on one communicator;
      * a collective inside a data-dependent branch sub-block (cond):
        ranks disagreeing on the predicate deadlock the ring; inside a
        loop sub-block it is a warning (trip counts must match on every
        rank, which the IR cannot prove).

    Programs with no collective ops skip the pass entirely."""
    program = ctx.program
    n_blocks = len(program.blocks)

    # who owns each sub-block (for the control-flow context of a ring)
    owner: Dict[int, object] = {}
    for block, idx, op in ctx.iter_ops():
        for _attr, tidx in _block_refs(op):
            if isinstance(tidx, int) and 0 <= tidx < n_blocks:
                owner.setdefault(tidx, op)

    def enclosing_control(block):
        """Nearest control-flow op owning `block` or an ancestor, or
        None for trunk blocks."""
        b = block
        seen = set()
        while b is not None and b.idx not in seen:
            seen.add(b.idx)
            op = owner.get(b.idx)
            if op is not None and op.type in _BRANCH_OPS + _LOOP_OPS:
                return op
            b = _safe_parent(program, b)
        return None

    any_collective = False
    ring_scopes: Dict[str, Set[int]] = {}  # ring -> block idxs using it
    staged: Dict[int, List] = {}   # stage -> [(op_idx, type, ring)]
    all_stages: Set[int] = set()   # every stage any op runs under
    for block, idx, op in ctx.iter_ops():
        if "pipeline_stage" in op.attrs and not op.type.endswith(_GRAD):
            all_stages.add(int(op.attrs["pipeline_stage"]))
        if not op.type.startswith("c_"):
            continue
        any_collective = True
        attrs = _effective_attrs(ctx, op)
        ring = attrs.get("ring_id")
        if not isinstance(ring, str) or not ring:
            continue  # distributed-lint reports the malformed ring_id
        ring_scopes.setdefault(ring, set()).add(block.idx)

        ctl = enclosing_control(block)
        if ctl is not None:
            if ctl.type in _BRANCH_OPS:
                yield ctx.diag(
                    "error",
                    f"collective {op.type!r} (ring {ring!r}) sits in "
                    f"the sub-block of a {ctl.type!r} op — ranks taking "
                    "different branches deadlock the ring",
                    block, idx, op,
                    hint="hoist the collective out of the branch, or "
                         "make the predicate provably rank-uniform")
            else:
                yield ctx.diag(
                    "warning",
                    f"collective {op.type!r} (ring {ring!r}) sits in "
                    f"the body of a {ctl.type!r} op — every rank must "
                    "run the same trip count or the ring deadlocks",
                    block, idx, op,
                    hint="prefer a fixed trip count shared by all "
                         "ranks")

        stage = op.attrs.get("pipeline_stage")
        if stage is not None:
            stage = int(stage)
            staged.setdefault(stage, []).append((idx, op.type, ring))
            if ring == _STAGE_AXIS and op.type not in _PIPELINE_HOPS:
                yield ctx.diag(
                    "error",
                    f"staged collective {op.type!r} at stage {stage} "
                    f"reuses ring {_STAGE_AXIS!r} — the stage axis's "
                    "communicator belongs to the pipeline schedule's "
                    "permutes; a per-stage reduction over it overlaps "
                    "the schedule's full-axis group",
                    block, idx, op,
                    hint="reduce over a dedicated axis (dp/tp) or "
                         "after the pipeline epilogue")

    if not any_collective:
        return

    # ring used from both the trunk and a control-flow sub-block: the
    # scopes execute under different schedules on one communicator
    for ring, scopes in sorted(ring_scopes.items()):
        sub = sorted(i for i in scopes if i != 0)
        if 0 in scopes and sub:
            yield ctx.diag(
                "warning",
                f"ring {ring!r} is used from the global block AND from "
                f"sub-block(s) {sub} — one communicator under two "
                "control-flow scopes is an overlapping-group hazard",
                program.blocks[0],
                hint="give control-flow-scoped collectives their own "
                     "ring (mesh axis)")

    # per-rank sequences: every stage the program runs ops under (a
    # stage with NO collectives on a shared ring is the imbalance case)
    # must issue identical (type) sequences per ring
    if not staged or len(all_stages) < 2:
        return
    stages = sorted(all_stages | set(staged))
    per_ring: Dict[str, Dict[int, List[str]]] = {}
    for s in stages:
        for _idx, typ, ring in staged.get(s, ()):
            per_ring.setdefault(ring, {}).setdefault(s, []).append(typ)
    # unstaged collectives run on all ranks uniformly — no check needed
    for ring, by_stage in sorted(per_ring.items()):
        if ring == _STAGE_AXIS:
            continue  # hop/reuse handled above
        seqs = {s: tuple(by_stage.get(s, ())) for s in stages}
        baseline_stage = min(s for s in stages if seqs[s])
        base = seqs[baseline_stage]
        for s in stages:
            if seqs[s] == base:
                continue
            if len(seqs[s]) != len(base):
                yield ctx.diag(
                    "error",
                    f"pipeline-stage collective imbalance on ring "
                    f"{ring!r}: stage {baseline_stage} issues "
                    f"{len(base)} collective(s) {list(base)} but stage "
                    f"{s} issues {len(seqs[s])} {list(seqs[s])} — ranks "
                    "wait at a collective their peers never reach",
                    program.blocks[0],
                    hint="every stage must issue the same collectives "
                         "on a shared ring (SPMD discipline)")
            else:
                yield ctx.diag(
                    "error",
                    f"cross-rank collective ordering mismatch on ring "
                    f"{ring!r}: stage {baseline_stage} issues "
                    f"{list(base)} but stage {s} issues "
                    f"{list(seqs[s])} — each rank blocks inside a "
                    "different collective and none completes",
                    program.blocks[0],
                    hint="issue collectives in one canonical order on "
                         "every rank")


@register_pass("sharding-consistency", order=72)
def check_sharding_consistency(ctx):
    """Multichip sharding annotations (layers.shard /
    data(sharding=...) / op dist_attr) validated at build time by
    re-running the spmd propagation (parallel/spmd.py) and re-emitting
    its findings: contradictory specs for one var and mesh-axis arity
    mismatches (spec longer than the tensor's rank, an axis naming two
    dims, an axis missing from the declared Program.mesh_axes) are
    errors; resharding hotspots (operands that force GSPMD to
    all-gather or reshard mid-graph) and non-divisible dims are
    warnings.  Programs with no sharding annotations skip the pass
    entirely (docs/performance.md 'Multichip sharding')."""
    program = ctx.program
    block = program.global_block()
    from ..parallel.spmd import has_annotations, propagate_sharding

    if not has_annotations(block):
        return

    plan = propagate_sharding(program)
    for f in plan.findings:
        op = (block.ops[f.op_idx]
              if f.op_idx is not None and f.op_idx < len(block.ops)
              else None)
        yield ctx.diag(f.severity, f.message, block, f.op_idx, op,
                       hint=f.hint)


@register_pass("donation-safety", order=75)
def check_donation_safety(ctx):
    """Vars hinted `donate=True` (layers.data(donate=True)) hand their
    device buffer to the jitted step for reuse — which is only legal
    when the buffer is provably dead once the step returns.  A donated
    fetch target (the caller reads that buffer after the call) or a
    read-only persistable (the next step reads it again) is flagged as
    an error HERE, at build time; the executors enforce the same plan
    via memory_optimization_transpiler.plan_donation and raise
    DonationError before tracing (docs/performance.md 'Memory')."""
    consumed: Set[str] = set()
    for _, _, op in ctx.iter_ops():
        consumed.update(op.input_names())
    for block in ctx.program.blocks:
        for name, v in block.vars.items():
            if not getattr(v, "donate", False):
                continue
            if isinstance(v, Parameter) or v.persistable:
                yield ctx.diag(
                    "error",
                    f"var {name!r} is hinted donate=True but is "
                    "persistable state — donating it would hand the "
                    "next step a deleted buffer",
                    block,
                    hint="drop the donate hint; read-write state is "
                         "already donated by the executor's plan",
                )
                continue
            if ctx.fetch_names and name in ctx.fetch_names:
                yield ctx.diag(
                    "error",
                    f"var {name!r} is hinted donate=True but is a fetch "
                    "target — the caller reads this buffer after the "
                    "step returns",
                    block,
                    hint="remove it from fetch_list, or drop the "
                         "donate hint",
                )
                continue
            if name not in consumed:
                yield ctx.diag(
                    "warning",
                    f"var {name!r} is hinted donate=True but no op "
                    "consumes it — the donation cannot be fulfilled",
                    block,
                    hint="feed the var to an op or drop the hint",
                )


@register_pass("inplace-alias", order=80)
def check_inplace_alias(ctx):
    """An op that binds the SAME var name as input and output mutates the
    value in place.  That is only safe when the registry declares the
    alias (optimizer Param->ParamOut etc.).  Undeclared aliasing with a
    later reader silently hands that reader the mutated value."""
    for block, idx, op in ctx.iter_ops():
        info = ctx.op_info(op)
        if info is None:
            continue
        in_names = set(op.input_names()) - set(EMPTY_VAR_NAMES)
        out_names = set(op.output_names()) - set(EMPTY_VAR_NAMES)
        shared = in_names & out_names
        if not shared:
            continue
        declared = set()
        for out_slot, in_slot in info.inplace.items():
            declared.update(
                set(op.output(out_slot)) & set(op.input(in_slot)))
        for n in sorted(shared - declared):
            has_later_reader = any(
                n in later.input_names()
                for later in block.ops[idx + 1:]
            )
            yield ctx.diag(
                "warning" if has_later_reader else "info",
                f"op {op.type!r} reads AND writes {n!r} without a "
                "declared in-place alias"
                + (" — a later op reads the mutated value"
                   if has_later_reader else ""),
                block, idx, op,
                hint="declare inplace={...} on the register_op call, "
                     "or write to a fresh output name",
            )
