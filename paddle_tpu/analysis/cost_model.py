"""Static cost model over the Program IR: roofline, peak HBM, comm volume.

ROADMAP item 5's kernel tier needs to know WHERE kernels pay off, and the
only instrument so far was compile-and-measure (`benchmark/harness`
`step_cost_analysis` — an XLA compile per question).  The Program IR
already carries everything a first-order answer needs: op descs, declared
shapes/dtypes, the PR 6 liveness machinery and the PR 9 `SpmdPlan`.  This
module is the compile-free estimator over that information:

  * `estimate_op` / `estimate_program` — per-op FLOP and HBM-traffic
    estimates driven by cost metadata on the registry `OpInfo`
    (`cost_kind` estimator classes + exact `cost_fn` overrides for the
    dense hot ops), rolled up per block and per program into a static
    roofline row (arithmetic intensity vs the device ridge point).  Ops
    with no metadata report as **unknown** — coverage is part of the
    result, never a silent zero.
  * `estimate_peak_hbm` — static peak-live-HBM of one step, reusing the
    memory layer's liveness (`ControlFlowGraph` last-touch — the same
    analysis behind `plan_dead_frees`) and the donation rules of
    `plan_donation`, so the number reflects dead-var freeing and buffer
    donation exactly like the executors run the step.
  * `estimate_comm` — per-mesh-axis communication VOLUME: gradient-sync
    all-reduce bytes over the batch axis (matching the PR 9 bucketed
    overlap lowering payload exactly — test-pinned against HLO-counted
    all-reduce bytes), row-parallel psums from `SpmdPlan.reduce_ops`,
    explicit `c_*` collective payloads, resharding-hotspot gather bytes
    quantified (the previously qualitative warning), and pserver send-op
    wire bytes.
  * serving-kernel cost entries (`SERVING_KERNELS`) — the decode-path
    kernels that never appear as Program ops (paged decode `step` /
    `step_window`, gather-through-block-table attention) registered with
    their shape metadata so `cli analyze` answers for generation model
    dirs too.

Byte convention: **traffic** (per-op reads + writes), the same side of
the roofline as XLA's `bytes accessed`; both over-count what fusion
keeps in registers, the static model more so (every op boundary counts),
which is why `benchmark/harness.static_vs_measured` pins the
estimated-vs-measured band instead of asserting equality.  Collective
bytes are logical payload bytes (the operand tensor), matching the
all-reduce operand shapes in optimized HLO.

Two analysis passes surface the model through the PR 3 verifier
(`cost-model`, `comm-volume`); `python -m paddle_tpu.cli analyze` prints
the tables and gates them against checked-in budgets (docs/analysis.md).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import registry as op_registry
from ..core.framework import EMPTY_VAR_NAMES, Parameter, grad_var_name
from ..core.registry import register_op_cost, set_op_cost_kind
from ..core.types import np_dtype
from .registry import register_pass

__all__ = [
    "OpCost",
    "ProgramCostEstimate",
    "CommEstimate",
    "estimate_op",
    "estimate_program",
    "estimate_peak_hbm",
    "estimate_comm",
    "ridge_point",
    "roofline_seconds",
    "DEVICE_SPECS",
    "DEFAULT_DEVICE",
    "DEFAULT_BATCH",
    "SERVING_KERNELS",
    "register_serving_kernel",
    "serving_kernel_cost",
    "analyze_generation_spec",
    "check_budget",
]

_GRAD = "_grad"

# assumed batch when a -1 dim has no runtime context (cli --batch / the
# calibration path pass the real one); reported in every summary so a
# roofline row is never mistaken for a measured number
DEFAULT_BATCH = 32

# device ridge points (bf16 peak FLOP/s, HBM bytes/s) — the ONE chip
# table; benchmark/harness reads it too, so the measured roofline and
# this compile-free estimate share every ridge point.  The default is
# the bench chip every committed artifact (BENCH_r*/MOE_r*/RIDGE_r*)
# was measured on
DEVICE_SPECS: Dict[str, Tuple[float, float]] = {
    "TPU v5 lite": (197e12, 819e9),   # v5e
    "TPU v5": (459e12, 2765e9),       # v5p
    "TPU v4": (275e12, 1228e9),
    "TPU v6 lite": (918e12, 1640e9),  # v6e / Trillium
}
DEFAULT_DEVICE = "TPU v5 lite"


def ridge_point(device: str = DEFAULT_DEVICE) -> float:
    """flop/byte at which `device` flips memory- to compute-bound."""
    peak, hbm = DEVICE_SPECS[device]
    return peak / hbm


def roofline_seconds(flops: float, bytes_: float,
                     device: str = DEFAULT_DEVICE) -> float:
    """Static roofline floor in SECONDS for work doing `flops` FLOPs
    and moving `bytes_` HBM bytes on `device` — max of the compute
    floor and the bandwidth floor.  The time-attribution plane
    publishes this per phase (``*_phase_static_seconds``) so the
    collector can band measured phase time against the static model
    (``paddle_tpu_calibration_ratio``; docs/observability.md "Time
    attribution")."""
    peak, hbm = DEVICE_SPECS[device]
    return max(float(flops) / peak, float(bytes_) / hbm)


@dataclasses.dataclass
class OpCost:
    """Static cost of one op desc.

    `flops` — floating-point operations (2*MACs for dense ops);
    `bytes` — HBM traffic: operand reads + result writes;
    `kind` — estimator class that produced the numbers ("unknown" when
    the registry carries no cost metadata for the type — the caller must
    surface these, they are NOT zero-cost);
    `note` — human detail (e.g. "2*M*K*N = 2*32*64*128").
    """

    flops: float = 0.0
    bytes: float = 0.0
    kind: str = "unknown"
    note: str = ""

    @property
    def known(self) -> bool:
        return self.kind != "unknown"


# ---------------------------------------------------------------------------
# estimator-class table for the registered op corpus
# ---------------------------------------------------------------------------

# flops per OUTPUT element by class ("reduction" counts INPUT elements);
# order-of-magnitude constants — the dense classes (matmul/conv/
# attention/moe, exact fns below) dominate every real model
_FLOPS_PER_ELEM = {
    "elementwise": 1.0,
    "optimizer": 4.0,   # axpy-ish update + accumulator math
    "norm": 8.0,        # mean/var/rsqrt/scale/shift
    "reduction": 2.0,
    "random": 2.0,
    "data": 0.0,
    "free": 0.0,
    "collective": 0.0,
    "embedding": 0.0,
    "host": 0.0,
    "control": 0.0,
}

_ELEMENTWISE = (
    "elementwise_add elementwise_sub elementwise_mul elementwise_div "
    "elementwise_max elementwise_min elementwise_pow relu tanh sigmoid "
    "exp abs square softsign reciprocal sqrt log softplus scale clip "
    "leaky_relu elu relu6 pow stanh hard_shrink soft_shrink brelu "
    "softshrink ceil floor round sign logsigmoid hard_sigmoid swish "
    "soft_relu tanh_shrink thresholded_relu prelu maxout minus cast "
    "equal not_equal less_than less_equal greater_than greater_equal "
    "logical_and logical_or logical_not logical_xor isfinite "
    "fill_zeros_like label_smooth increment assign clip_by_norm "
    "cumsum sum dropout cos_sim huber_loss hinge_loss log_loss "
    "rank_loss margin_rank_loss modified_huber_loss smooth_l1_loss "
    "squared_l2_distance bilinear_tensor_product lrn conv_shift "
    "row_conv"
).split()

_OPTIMIZER = ("sgd momentum adam adamax adagrad adadelta rmsprop ftrl "
              "decayed_adagrad proximal_adagrad proximal_gd "
              "average_accumulates pruning_mask").split()

_NORM = "batch_norm layer_norm l1_norm norm squared_l2_norm".split()

_REDUCTION = (
    "reduce_sum reduce_mean reduce_max reduce_min reduce_prod mean "
    "softmax sequence_softmax softmax_with_cross_entropy cross_entropy "
    "sigmoid_cross_entropy_with_logits accuracy argmax top_k "
    "sequence_pool pool2d pool3d max_pool2d_with_index "
    "max_pool3d_with_index spp roi_pool unpool auc precision_recall "
    "chunk_eval edit_distance one_hot nce hsigmoid warpctc "
    "linear_chain_crf crf_decoding ctc_align detection_map "
    "multiclass_nms mine_hard_examples bipartite_match iou_similarity "
    "positive_negative_pair"
).split()

_RANDOM = ("uniform_random gaussian_random "
           "uniform_random_batch_size_like").split()

# layout/movement ops: no flops, real traffic
_DATA = (
    "transpose concat split gather scatter pad slice crop expand stack "
    "reverse multiplex sequence_concat sequence_expand sequence_pad "
    "sequence_unpad sequence_slice sequence_erase sequence_reshape "
    "sequence_mask im2sequence beam_search beam_search_decode "
    "lod_reset lod_tensor_to_array array_to_lod_tensor write_to_array "
    "read_from_array merge_lod_tensor split_lod_tensor "
    "split_selected_rows reorder_lod_tensor_by_rank box_coder "
    "prior_box target_assign assign_value fill fill_constant "
    "fill_constant_batch_size_like"
).split()

# metadata-only / bitcast ops: neither flops nor HBM traffic
_FREE = (
    "reshape flatten squeeze unsqueeze shape is_empty lod_rank_table "
    "lod_array_length max_sequence_len shrink_rnn_memory "
    "rnn_memory_helper get_places feed fetch"
).split()

_COLLECTIVE = ("c_allreduce_sum c_allreduce_mean c_allreduce_max "
               "c_allgather c_reducescatter c_broadcast "
               "c_ppermute").split()

# recurrent / control-flow op families: bodies live in sub-blocks (the
# program walk costs those blocks directly), cells are elementwise-ish
_CONTROL = ("while cond conditional_block parallel_do recurrent "
            "dynamic_rnn recompute").split()
_RNN_CELL = ("lstm lstm_unit lstmp gru gru_unit".split())

# lookup_table_grad is its own registration (SelectedRows path) — the
# dense table-grad write is real traffic, costed generically
_EMBEDDING = ("lookup_table", "lookup_table_grad")


def _build_kind_table() -> Dict[str, str]:
    table: Dict[str, str] = {}
    for names, kind in (
        (_ELEMENTWISE, "elementwise"),
        (_OPTIMIZER, "optimizer"),
        (_NORM, "norm"),
        (_REDUCTION, "reduction"),
        (_RANDOM, "random"),
        (_DATA, "data"),
        (_FREE, "free"),
        (_COLLECTIVE, "collective"),
        (_CONTROL, "control"),
        (_RNN_CELL, "elementwise"),
        (_EMBEDDING, "embedding"),
        (("mul", "matmul"), "matmul"),
        (("conv2d", "depthwise_conv2d", "conv2d_transpose", "conv3d",
          "conv3d_transpose", "sequence_conv"), "conv"),
        (("flash_attention",), "attention"),
        (("moe_ffn",), "moe"),
    ):
        for n in names:
            table[n] = kind
    return table


_KIND_TABLE = _build_kind_table()


def _install_kind_table():
    """Write the estimator classes onto the registry OpInfo corpus (the
    per-op metadata surface); explicit `cost=` kwargs on register_op and
    `register_op_cost` fns take precedence and are never overwritten.
    Called at import AND lazily from `estimate_op` — op modules that
    register after this module imports still get their metadata."""
    for n, kind in _KIND_TABLE.items():
        set_op_cost_kind(n, kind)


# backward work per forward FLOP by class: a dense op's backward is two
# GEMMs per forward GEMM; pointwise backward is ~the forward
_GRAD_MULT = {"matmul": 2.0, "conv": 2.0, "attention": 2.5, "moe": 2.0}


# ---------------------------------------------------------------------------
# shape resolution
# ---------------------------------------------------------------------------


def _dtype_bytes(dtype) -> int:
    if dtype is None:
        return 4
    try:
        return int(np_dtype(dtype).itemsize)
    except Exception:
        return 4


def _make_resolver(block, batch):
    """resolve(name) -> (shape, dtype) with -1 dims already substituted
    (ancestor-chain lookup, None for unresolvable/undeclared).

    Build-time inference substitutes a prime sentinel for unknown dims
    (core/shape_inference._SENTINEL) and only maps EXACT sentinel dims
    back to -1 — a reshape that folds the batch into another dim leaves
    `sentinel * k` concrete on the var.  Those dims are batch-dependent
    too: map them to `batch * k` here, or one contaminated reshape
    inflates the whole roofline by 8191/batch."""
    from ..core.shape_inference import _SENTINEL

    def fix(d):
        if d < 0:
            return batch
        d = int(d)
        if d >= _SENTINEL and d % _SENTINEL == 0:
            return (d // _SENTINEL) * batch
        return d

    def resolve(name):
        b = block
        seen = set()
        while b is not None and b.idx not in seen:
            seen.add(b.idx)
            v = b.vars.get(name)
            if v is not None:
                if v.shape is None:
                    return None
                return tuple(fix(d) for d in v.shape), v.dtype
            b = b.parent
        return None

    return resolve


def _slot_bytes(op, resolve, slots) -> Tuple[float, int]:
    """(bytes, unresolved-count) over the named vars of `slots`."""
    total, missing = 0.0, 0
    for names in slots.values():
        for n in names:
            if n in EMPTY_VAR_NAMES:
                continue
            r = resolve(n)
            if r is None:
                missing += 1
                continue
            shape, dtype = r
            total += float(np.prod(shape, dtype=np.float64) if shape
                           else 1.0) * _dtype_bytes(dtype)
    return total, missing


def _generic_bytes(op, resolve) -> float:
    rb, _ = _slot_bytes(op, resolve, op.inputs)
    wb, _ = _slot_bytes(op, resolve, op.outputs)
    return rb + wb


def _out_elems(op, resolve) -> float:
    n = 0.0
    for names in op.outputs.values():
        for nm in names:
            if nm in EMPTY_VAR_NAMES:
                continue
            r = resolve(nm)
            if r is not None:
                n += float(np.prod(r[0], dtype=np.float64) if r[0]
                           else 1.0)
    return n


def _in_elems(op, resolve) -> float:
    n = 0.0
    for names in op.inputs.values():
        for nm in names:
            if nm in EMPTY_VAR_NAMES:
                continue
            r = resolve(nm)
            if r is not None:
                n += float(np.prod(r[0], dtype=np.float64) if r[0]
                           else 1.0)
    return n


# ---------------------------------------------------------------------------
# exact estimators for the dense hot ops
# ---------------------------------------------------------------------------


@register_op_cost("mul")
def _mul_cost(op, resolve):
    """Flatten-to-2D GEMM: flops = 2*M*K*N with M = prod(x[:xd]),
    K = prod(x[xd:]), N = prod(y[yd:])."""
    rx, ry = resolve(op.input("X")[0]), resolve(op.input("Y")[0])
    if rx is None or ry is None:
        return OpCost(kind="unknown", note="mul operand shape undeclared")
    xs, ys = rx[0], ry[0]
    xd = int(op.attrs.get("x_num_col_dims", 1))
    yd = int(op.attrs.get("y_num_col_dims", 1))
    m = float(np.prod(xs[:xd], dtype=np.float64)) if xd else 1.0
    k = float(np.prod(xs[xd:], dtype=np.float64))
    n = float(np.prod(ys[yd:], dtype=np.float64))
    return OpCost(2.0 * m * k * n, _generic_bytes(op, resolve), "matmul",
                  f"2*{m:.0f}*{k:.0f}*{n:.0f}")


@register_op_cost("matmul")
def _matmul_cost(op, resolve):
    rx, ry = resolve(op.input("X")[0]), resolve(op.input("Y")[0])
    if rx is None or ry is None:
        return OpCost(kind="unknown",
                      note="matmul operand shape undeclared")
    xs = list(rx[0]) or [1]
    ys = list(ry[0]) or [1]
    if op.attrs.get("transpose_X"):
        xs[-2:] = xs[-2:][::-1] if len(xs) >= 2 else xs
    if op.attrs.get("transpose_Y"):
        ys[-2:] = ys[-2:][::-1] if len(ys) >= 2 else ys
    m = float(xs[-2]) if len(xs) >= 2 else 1.0
    k = float(xs[-1])
    n = float(ys[-1]) if len(ys) >= 2 else 1.0
    batch = max(
        float(np.prod(xs[:-2], dtype=np.float64)) if len(xs) > 2 else 1.0,
        float(np.prod(ys[:-2], dtype=np.float64)) if len(ys) > 2 else 1.0)
    return OpCost(2.0 * batch * m * k * n, _generic_bytes(op, resolve),
                  "matmul", f"2*{batch:.0f}*{m:.0f}*{k:.0f}*{n:.0f}")


def _conv_cost(op, resolve):
    """2 * out_elems * (Cin/groups) * prod(kernel) — Output shape from
    build-time inference, filter gives kernel + channel counts."""
    fil = (op.input("Filter") or [None])[0]
    outs = [n for n in op.output_names() if n not in EMPTY_VAR_NAMES]
    rf = resolve(fil) if fil else None
    ro = resolve(outs[0]) if outs else None
    if rf is None or ro is None:
        return OpCost(kind="unknown", note="conv shapes undeclared")
    fshape = rf[0]
    groups = int(op.attrs.get("groups", 1) or 1)
    # conv filter [Cout, Cin/g, *k]; transpose filter [Cin, Cout/g, *k]
    cin_per_group = float(fshape[1])
    kernel = float(np.prod(fshape[2:], dtype=np.float64))
    out_elems = float(np.prod(ro[0], dtype=np.float64))
    del groups  # Cin/g is already the per-group contraction depth
    flops = 2.0 * out_elems * cin_per_group * kernel
    return OpCost(flops, _generic_bytes(op, resolve), "conv",
                  f"2*{out_elems:.0f}*{cin_per_group:.0f}*{kernel:.0f}")


for _t in ("conv2d", "depthwise_conv2d", "conv2d_transpose", "conv3d",
           "conv3d_transpose"):
    register_op_cost(_t)(_conv_cost)


@register_op_cost("flash_attention")
def _flash_attention_cost(op, resolve):
    """Q/K/V [B, S, H, Dh]: 2 GEMMs (QK^T, att*V) = 4*B*H*Sq*Sk*Dh
    flops (halved causal); bytes are q/k/v/out ONLY — the fused kernel
    never materializes the Sq x Sk score matrix (the training-side HBM
    point of the Pallas tier)."""
    rq = resolve(op.input("Q")[0])
    rk = resolve(op.input("K")[0])
    if rq is None or rk is None:
        return OpCost(kind="unknown",
                      note="attention operand shape undeclared")
    b, sq = rq[0][0], rq[0][1]
    h = rq[0][2] if len(rq[0]) > 2 else 1
    dh = rq[0][3] if len(rq[0]) > 3 else rq[0][-1]
    sk = rk[0][1]
    flops = 4.0 * b * h * sq * sk * dh
    if op.attrs.get("causal"):
        flops *= 0.5
    return OpCost(flops, _generic_bytes(op, resolve), "attention",
                  f"4*{b}*{h}*{sq}*{sk}*{dh}"
                  + (" causal/2" if op.attrs.get("causal") else ""))


@register_op_cost("moe_ffn")
def _moe_ffn_cost(op, resolve):
    """GShard dense form (parallel/moe.py): gating GEMM + dispatch/
    combine einsums + E experts x capacity tokens through the FFN pair,
    capacity = cf * top_k * T / E."""
    rx = resolve(op.input("X")[0])
    rwi = resolve(op.input("WIn")[0])
    if rx is None or rwi is None:
        return OpCost(kind="unknown", note="moe operand shape undeclared")
    xs = rx[0]
    t = float(np.prod(xs[:-1], dtype=np.float64))
    d = float(xs[-1])
    e, _, di = (float(rwi[0][0]), float(rwi[0][1]), float(rwi[0][2]))
    top_k = int(op.attrs.get("top_k", 1) or 1)
    cf = float(op.attrs.get("capacity_factor", 1.25) or 1.25)
    cap = max(1.0, cf * top_k * t / e)
    gate = 2.0 * t * d * e
    dispatch = 2.0 * 2.0 * t * e * cap * d      # td,tec->ecd and back
    experts = 2.0 * e * cap * (2.0 * d * di)    # FFN pair on capacity
    return OpCost(gate + dispatch + experts, _generic_bytes(op, resolve),
                  "moe",
                  f"E={e:.0f} cap={cap:.0f} top_k={top_k} cf={cf}")


@register_op_cost("lookup_table")
def _lookup_table_cost(op, resolve):
    """Gather: reads the touched rows + ids, writes the vectors — the
    table itself is not streamed."""
    rw = resolve(op.input("W")[0])
    rids = resolve(op.input("Ids")[0])
    if rw is None or rids is None:
        return OpCost(kind="unknown",
                      note="lookup operand shape undeclared")
    n_ids = float(np.prod(rids[0], dtype=np.float64))
    width = float(rw[0][-1])
    row_bytes = width * _dtype_bytes(rw[1])
    return OpCost(0.0, n_ids * (2.0 * row_bytes + 8.0), "embedding",
                  f"{n_ids:.0f} rows x {width:.0f}")


# ---------------------------------------------------------------------------
# per-op / per-program estimation
# ---------------------------------------------------------------------------


class _FwdShim:
    """Forward-shaped view of a generic '<t>_grad' desc: a grad desc
    binds the forward's inputs AND outputs as its own inputs, so the
    forward cost fn can run against it with the slots re-partitioned."""

    def __init__(self, grad_op, fwd_info):
        self.type = fwd_info.type
        self.attrs = grad_op.attrs
        self.inputs = {s: grad_op.inputs.get(s, [])
                       for s in fwd_info.inputs}
        self.outputs = {s: grad_op.inputs.get(s, [])
                        for s in fwd_info.outputs}

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    def input_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self):
        return [n for vs in self.outputs.values() for n in vs]


def _fwd_shim(grad_op, fwd_info):
    return _FwdShim(grad_op, fwd_info)


def estimate_op(op, block, batch_size: int = DEFAULT_BATCH) -> OpCost:
    """Static cost of one op desc (shapes resolved against `block`'s
    ancestor chain, -1 dims -> `batch_size`).  Unregistered or
    metadata-less types return kind="unknown" — never a silent zero."""
    resolve = _make_resolver(block, batch_size)
    try:
        info = op_registry.get_op_info(op.type)
    except KeyError:
        return OpCost(kind="unknown", note="op type not registered")

    is_grad = op.type.endswith(_GRAD) and info.type != op.type
    if info.cost_fn is not None:
        target = _fwd_shim(op, info) if is_grad else op
        cost = info.cost_fn(target, resolve)
        if is_grad and cost.known:
            mult = _GRAD_MULT.get(cost.kind, 1.0)
            cost = OpCost(cost.flops * mult, _generic_bytes(op, resolve),
                          cost.kind, cost.note + f" (grad x{mult})")
        return cost

    kind = info.cost_kind
    if kind is None and info.type in _KIND_TABLE:
        kind = _KIND_TABLE[info.type]
        info.cost_kind = kind  # memoize onto the registry metadata
    if kind is None and info.type.endswith(_GRAD):
        # explicitly-registered grad lowerings (dropout_grad,
        # split/merge_lod_tensor_grad) resolve to their OWN OpInfo, so
        # the forward-op fallback in get_op_info never fires — inherit
        # the forward type's class instead of reporting unknown
        base = _KIND_TABLE.get(info.type[: -len(_GRAD)])
        if base is not None:
            kind = info.cost_kind = base
    if kind is None:
        if info.host:
            kind = "host"
        elif any(isinstance(v, dict) and "__block__" in v
                 for v in op.attrs.values()):
            kind = "control"
        else:
            return OpCost(kind="unknown",
                          note=f"no cost metadata for {op.type!r}")
    if kind in ("free", "host", "control"):
        return OpCost(0.0, 0.0, kind)
    per_elem = _FLOPS_PER_ELEM.get(kind, 1.0)
    elems = (_in_elems(op, resolve) if kind == "reduction"
             else _out_elems(op, resolve))
    flops = per_elem * elems
    if is_grad:
        flops *= _GRAD_MULT.get(kind, 1.0)
    return OpCost(flops, _generic_bytes(op, resolve), kind)


@dataclasses.dataclass
class ProgramCostEstimate:
    """Roll-up of `estimate_op` over every block of one program."""

    batch_size: int
    device: str
    rows: List[tuple]                 # (block_idx, op_idx, op_type, OpCost)
    block_totals: Dict[int, Tuple[float, float]]   # {blk: (flops, bytes)}
    total_flops: float
    total_bytes: float
    unknown_types: Dict[str, int]     # {op_type: count} with no metadata
    n_ops: int
    peak_hbm: Dict                    # estimate_peak_hbm result

    @property
    def ai(self) -> Optional[float]:
        if not self.total_bytes:
            return None
        return self.total_flops / self.total_bytes

    def roofline(self) -> Dict:
        """Static roofline fields in the harness vocabulary: AI vs the
        device ridge point, the two ms floors, and the verdict."""
        peak, hbm = DEVICE_SPECS[self.device]
        out = {
            "device": self.device,
            "batch_size": self.batch_size,
            "est_flops": self.total_flops,
            "est_hbm_traffic_gb": round(self.total_bytes / 1e9, 3),
            "est_peak_hbm_gb": round(
                self.peak_hbm.get("peak_bytes", 0) / 1e9, 3),
            "n_ops": self.n_ops,
            "unknown_ops": sum(self.unknown_types.values()),
            "unknown_types": sorted(self.unknown_types),
        }
        if self.total_bytes:
            ai = self.total_flops / self.total_bytes
            out["ai_flop_per_byte"] = round(ai, 1)
            out["ridge_flop_per_byte"] = round(peak / hbm, 1)
            out["hbm_floor_ms"] = round(self.total_bytes / hbm * 1000, 3)
            out["compute_floor_ms"] = round(
                self.total_flops / peak * 1000, 3)
            out["bound"] = ("memory" if out["hbm_floor_ms"]
                            >= out["compute_floor_ms"] else "compute")
        return out

    def top_memory_bound(self, n: int = 5) -> List[tuple]:
        """The ranked worklist for the kernel tier: known-cost ops by
        traffic, with per-op AI (lowest-AI heavy ops first)."""
        ranked = sorted(
            (r for r in self.rows if r[3].known and r[3].bytes > 0),
            key=lambda r: -r[3].bytes)
        return [(blk, idx, t,
                 round(c.flops / c.bytes, 1) if c.bytes else 0.0,
                 c.bytes) for blk, idx, t, c in ranked[:n]]


def estimate_program(program, batch_size: int = DEFAULT_BATCH,
                     feed_names: Optional[Sequence[str]] = None,
                     fetch_names: Optional[Sequence[str]] = None,
                     device: str = DEFAULT_DEVICE) -> ProgramCostEstimate:
    """Walk every block, cost every op, and fold in the static peak-HBM
    estimate.  Sub-block ops are counted ONCE (a while body's trip count
    is not statically known — the summary says so via the 'control' ops
    in the table)."""
    rows: List[tuple] = []
    block_totals: Dict[int, Tuple[float, float]] = {}
    unknown: Dict[str, int] = {}
    tf = tb = 0.0
    n_ops = 0
    for block in program.blocks:
        bf = bb = 0.0
        for idx, op in enumerate(block.ops):
            c = estimate_op(op, block, batch_size)
            rows.append((block.idx, idx, op.type, c))
            n_ops += 1
            if not c.known:
                unknown[op.type] = unknown.get(op.type, 0) + 1
                continue
            bf += c.flops
            bb += c.bytes
        block_totals[block.idx] = (bf, bb)
        tf += bf
        tb += bb
    peak = estimate_peak_hbm(program, batch_size=batch_size,
                             feed_names=feed_names,
                             fetch_names=fetch_names)
    return ProgramCostEstimate(
        batch_size=batch_size, device=device, rows=rows,
        block_totals=block_totals, total_flops=tf, total_bytes=tb,
        unknown_types=unknown, n_ops=n_ops, peak_hbm=peak)


# ---------------------------------------------------------------------------
# static peak HBM (liveness + donation, the PR 6 machinery)
# ---------------------------------------------------------------------------


def estimate_peak_hbm(program, batch_size: int = DEFAULT_BATCH,
                      feed_names: Optional[Sequence[str]] = None,
                      fetch_names: Optional[Sequence[str]] = None) -> Dict:
    """Static peak live HBM of one step of the global block.

    Persistables count once (read-write state is donated by the
    executors — `plan_donation.states` — so old and new buffers never
    coexist).  Temporaries live from first def to last touch (the
    `ControlFlowGraph` liveness behind `plan_dead_frees`); fetch targets
    and sub-block-referenced names live to the end; a feed outside the
    donation plan (fetched / never consumed) also survives the whole
    step.  Returns {peak_bytes, persistable_bytes, peak_temp_bytes,
    peak_op_idx, no_free_peak_bytes} — `no_free_peak_bytes` is the same
    walk with every temp held to the end, i.e. what the step would cost
    without dead-var freeing."""
    from ..memory_optimization_transpiler import (ControlFlowGraph,
                                                  _sub_block_names,
                                                  plan_donation)

    block = program.global_block()
    resolve = _make_resolver(block, batch_size)

    def nbytes(name) -> float:
        r = resolve(name)
        if r is None:
            return 0.0
        shape, dtype = r
        return float(np.prod(shape, dtype=np.float64) if shape
                     else 1.0) * _dtype_bytes(dtype)

    persistable = set()
    persist_bytes = 0.0
    for v in program.list_vars():
        if ((v.persistable or isinstance(v, Parameter))
                and v.name not in persistable):
            persistable.add(v.name)
            persist_bytes += nbytes(v.name)

    ops = block.ops
    n = len(ops)
    if n == 0:
        return {"peak_bytes": persist_bytes,
                "persistable_bytes": persist_bytes,
                "peak_temp_bytes": 0.0, "peak_op_idx": 0,
                "no_free_peak_bytes": persist_bytes}

    cfg = ControlFlowGraph(ops)
    last = cfg.last_touch()
    first_def: Dict[str, int] = {}
    for i, op in enumerate(ops):
        for nm in op.output_names():
            if nm and nm not in EMPTY_VAR_NAMES:
                first_def.setdefault(nm, i)

    produced = set(first_def)
    if feed_names is None:
        # feeds: consumed, never produced, not persistable
        feed_names = [nm for nm in last
                      if nm not in produced and nm not in persistable
                      and nm not in EMPTY_VAR_NAMES]
    fetch = {str(f) for f in (fetch_names or ())}
    protected = _sub_block_names(program) | fetch
    plan = plan_donation(program, feed_names, fetch)

    delta = np.zeros(n + 1, dtype=np.float64)
    nofree = 0.0
    for name in set(last) | produced:
        if (not name or name in EMPTY_VAR_NAMES
                or name in persistable):
            continue
        b = nbytes(name)
        if not b:
            continue
        nofree += b
        lo = first_def.get(name, 0)  # feeds live from step entry
        if name in protected or (name in (feed_names or ())
                                 and name not in plan.feeds):
            hi = n - 1  # survives the step (fetched / non-donatable)
        else:
            hi = last.get(name, lo)
        delta[lo] += b
        delta[hi + 1] -= b
    live = np.cumsum(delta[:n])
    peak_idx = int(np.argmax(live)) if n else 0
    peak_temp = float(live[peak_idx]) if n else 0.0
    return {
        "peak_bytes": persist_bytes + peak_temp,
        "persistable_bytes": persist_bytes,
        "peak_temp_bytes": peak_temp,
        "peak_op_idx": peak_idx,
        "no_free_peak_bytes": persist_bytes + nofree,
    }


# ---------------------------------------------------------------------------
# communication volume (the PR 9 plan, quantified)
# ---------------------------------------------------------------------------

_COLLECTIVE_KIND = {
    "c_allreduce_sum": "all_reduce", "c_allreduce_mean": "all_reduce",
    "c_allreduce_max": "all_reduce", "c_allgather": "all_gather",
    "c_reducescatter": "reduce_scatter", "c_broadcast": "broadcast",
    "c_ppermute": "permute",
}


@dataclasses.dataclass
class CommEstimate:
    """Per-mesh-axis communication volume of one step.

    `rows`: (axis, kind, bytes, detail) — kind in {all_reduce,
    all_gather, reduce_scatter, broadcast, permute, all_to_all, reshard,
    wire}.  Bytes are logical payload bytes (the operand tensor), the
    same convention as the operand shapes of the collective instructions
    in optimized HLO — the dp gradient-sync row matches the PR 9
    bucketed-overlap lowering's all-reduce bytes EXACTLY (test-pinned).
    """

    rows: List[tuple] = dataclasses.field(default_factory=list)

    def add(self, axis, kind, nbytes, detail=""):
        if nbytes:
            self.rows.append((str(axis), kind, float(nbytes), detail))

    def by_axis(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for axis, kind, b, _ in self.rows:
            out.setdefault(axis, {})
            out[axis][kind] = out[axis].get(kind, 0.0) + b
        return out

    def total_bytes(self) -> float:
        return sum(b for _, _, b, _ in self.rows)


def estimate_comm(program, mesh_axes: Optional[Dict[str, int]] = None,
                  batch_axis: str = "dp",
                  batch_size: int = DEFAULT_BATCH,
                  fetch_names: Optional[Sequence[str]] = None,
                  ) -> CommEstimate:
    """Static per-axis comm volume for `program` on a mesh.

    Sources, in order: explicit `c_*` collective ops (payload = output
    tensor); gradient sync over `batch_axis` for training programs
    (payload = every trainable param's grad + each scalar mean-combined
    fetch — exactly the bucketed-overlap all-reduce payload); pending
    psums from the sharding propagation (`SpmdPlan.reduce_ops`, the
    row-parallel matmul reductions); resharding hotspots quantified
    (bytes of the operand GSPMD must gather); pserver `send` ops as
    wire bytes.  With no mesh (none declared on the program, none
    passed) only the explicit-collective and wire rows apply."""
    from ..parallel.spmd import has_annotations, propagate_sharding

    block = program.global_block()
    resolve = _make_resolver(block, batch_size)
    mesh = dict(mesh_axes) if mesh_axes is not None else (
        dict(program.mesh_axes) if program.mesh_axes else None)
    est = CommEstimate()

    def nbytes(name) -> float:
        r = resolve(name)
        if r is None:
            return 0.0
        return float(np.prod(r[0], dtype=np.float64) if r[0]
                     else 1.0) * _dtype_bytes(r[1])

    # 1. explicit collectives, any block
    for blk in program.blocks:
        for op in blk.ops:
            kind = _COLLECTIVE_KIND.get(op.type)
            if kind is None:
                continue
            try:
                info = op_registry.get_op_info(op.type)
                attrs = {**info.attrs, **op.attrs}
            except KeyError:
                attrs = op.attrs
            ring = attrs.get("ring_id", "?")
            names = op.output_names() or op.input_names()
            b = sum(nbytes(nm) for nm in names
                    if nm not in EMPTY_VAR_NAMES)
            est.add(ring, kind, b, f"{op.type} (block {blk.idx})")

    # 2. gradient sync over the batch axis (training program on a mesh)
    if mesh and int(mesh.get(batch_axis, 1)) > 1:
        produced = {nm for op in block.ops for nm in op.output_names()}
        grad_bytes, n_grads = 0.0, 0
        for v in block.vars.values():
            if isinstance(v, Parameter) and getattr(v, "trainable", True):
                if grad_var_name(v.name) in produced:
                    grad_bytes += nbytes(v.name)
                    n_grads += 1
        if n_grads:
            est.add(batch_axis, "all_reduce", grad_bytes,
                    f"gradient sync ({n_grads} grads)")
        for f in fetch_names or ():
            v = block.vars.get(str(f))
            if v is None or (v.shape and v.shape[0] == -1):
                continue  # per-row fetches stay sharded
            if v.op is not None and v.op.type in ("mean", "accuracy"):
                est.add(batch_axis, "all_reduce", nbytes(v.name),
                        f"fetch combine ({v.name})")

    # 3. sharding-annotation derived rows
    if has_annotations(block):
        plan = propagate_sharding(program, mesh_axes=mesh,
                                  batch_axis=batch_axis)
        for idx, axes in sorted(plan.reduce_ops.items()):
            op = block.ops[idx]
            out = (op.outputs.get("Out") or [None])[0]
            b = nbytes(out) if out else 0.0
            for ax in axes:
                est.add(ax, "all_reduce", b,
                        f"row-parallel {op.type} psum (op {idx})")
        for f in plan.findings:
            if f.severity != "warning" or "all-gather" not in f.message:
                continue
            m = re.search(r"input '([^']+)'", f.message)
            if not m or f.op_idx is None:
                continue
            operand = m.group(1)
            from ..core.framework import sharding_axes

            # the gather is over the FEATURE dim — attribute its bytes
            # to the feature entry's axes, not the batch sharding that
            # rode along on dim 0
            spec = plan.var_specs.get(operand)
            feat = spec[-1] if spec else None
            axes = (sharding_axes((feat,)) if feat is not None
                    else sharding_axes(spec)) or ["?"]
            est.add(",".join(sorted(set(axes))), "reshard",
                    nbytes(operand),
                    f"{f.op_type} gathers {operand!r} (op {f.op_idx})")

    # 4. pserver wire traffic
    for op in block.ops:
        if op.type != "send":
            continue
        sent = sum(nbytes(nm) for nm in op.input("X")
                   if nm not in EMPTY_VAR_NAMES)
        pulled = sum(nbytes(nm) for nm in op.output("Out")
                     if nm not in EMPTY_VAR_NAMES)
        est.add("wire", "wire", sent + pulled,
                f"send op ({len(op.input('X'))} grads out, "
                f"{len(op.output('Out'))} params back)")
    return est


# ---------------------------------------------------------------------------
# serving-path kernels (never Program ops — spec-driven entries)
# ---------------------------------------------------------------------------

SERVING_KERNELS: Dict[str, Callable] = {}


def register_serving_kernel(name: str):
    """Register `fn(spec, **kw) -> dict` as the cost entry for a named
    serving kernel (the decode-path functions that never appear as
    Program ops).  The entry documents its operand shapes in the
    returned dict (`shapes` key) so `cli analyze` can print them."""

    def deco(fn):
        SERVING_KERNELS[name] = fn
        return fn

    return deco


def serving_kernel_cost(name: str, spec: Dict, **kw) -> Dict:
    if name not in SERVING_KERNELS:
        raise KeyError(f"serving kernel {name!r} has no cost entry; "
                       f"known: {sorted(SERVING_KERNELS)}")
    return SERVING_KERNELS[name](spec, **kw)


def _kv_elem_bytes(kv_dtype: str, block_size: int, d_model: int) -> float:
    """Resident bytes per K/V element, matching the paged decoder's own
    accounting (models/transformer.build_lm_paged_decoder
    `bytes_per_block`): int8 stores one f32 scale per (layer, block), so
    the per-element overhead is 4/(block_size*d_model) — NOT a flat
    surcharge."""
    if kv_dtype == "bf16":
        return 2.0
    if kv_dtype == "int8":
        return 1.0 + 4.0 / (block_size * d_model)
    return 4.0


def _spec_dims(spec: Dict):
    d = int(spec["d_model"])
    h = int(spec["n_heads"])
    layers = int(spec["n_layers"])
    v = int(spec["vocab_size"])
    di = int(spec.get("d_inner") or 4 * d)
    bs = int(spec.get("block_size", 16))
    nb = int(spec.get("max_blocks_per_seq", 64))
    return d, h, layers, v, di, bs, nb


def _lm_param_bytes(spec: Dict) -> float:
    """f32 parameter bytes of the paged-decoder LM (models/transformer
    `_lm_param_structure`): token embedding + position table + per-layer
    4 attention projections + FFN pair + layer norms + logits."""
    d, _, layers, v, di, bs, nb = _spec_dims(spec)
    max_len = bs * nb
    per_layer = 4 * (d * d + d) + (d * di + di) + (di * d + d) + 4 * d
    return 4.0 * (v * d + max_len * d + layers * per_layer
                  + 2 * d + d * v + v)


@register_serving_kernel("paged_attention_gather")
def _paged_attention_gather_cost(spec: Dict, slots: int = 1,
                                 context: Optional[int] = None,
                                 kv_dtype: str = "fp32", **_) -> Dict:
    """Gather-through-block-table attention for ONE query position per
    slot: K/V [n_layers, blocks, block_size, d_model] gathered through
    the table to `context` logical positions, dequantized, then QK^T +
    att*V (2*ctx*d each, per layer).

    Bytes charge BOTH legs of the composition: the pool reads in
    storage precision AND the logical-order f32 gathered copy the XLA
    path materializes (written, then re-read by the einsums) — the
    traffic the fused `paged_attention_decode` kernel deletes."""
    d, h, layers, v, di, bs, nb = _spec_dims(spec)
    ctx = int(context if context is not None else bs * nb)
    kvb = _kv_elem_bytes(kv_dtype, bs, d)
    flops = slots * layers * 4.0 * ctx * d
    pool_bytes = slots * layers * 2.0 * ctx * d * kvb
    copy_bytes = slots * layers * 2.0 * ctx * d * 8.0
    return {
        "kernel": "paged_attention_gather",
        "shapes": {"pool": f"[{layers}, blocks, {bs}, {d}] x2 ({kv_dtype})",
                   "tables": f"[{slots}, {nb}] int32",
                   "query": f"[{slots}, {h}, {d // max(h, 1)}]"},
        "flops": flops, "bytes": pool_bytes + copy_bytes,
        "pool_bytes": pool_bytes, "copy_bytes": copy_bytes,
        "context": ctx, "slots": slots,
    }


@register_serving_kernel("paged_attention_decode")
def _paged_attention_decode_cost(spec: Dict, slots: int = 1,
                                 context: Optional[int] = None,
                                 kv_dtype: str = "fp32",
                                 window: int = 1, **_) -> Dict:
    """The fused Pallas decode-attention kernel
    (kernels/paged_attention.py): K/V blocks stream through the block
    table straight into VMEM, dequantized in-lane — same flops as the
    gather composition, but the XLA path's logical-order f32 copy of
    the gathered context (written then re-read in HBM) never exists.
    `gather_copy_bytes_avoided` quantifies that saved traffic."""
    d, h, layers, v, di, bs, nb = _spec_dims(spec)
    ctx = int(context if context is not None else bs * nb)
    kvb = _kv_elem_bytes(kv_dtype, bs, d)
    flops = slots * window * layers * 4.0 * ctx * d
    # pool-block reads only, in storage precision: q/out traffic is the
    # step row's act_bytes, and the oracle's logical-order f32 copy
    # (write + re-read) simply never exists on this path
    pool_bytes = slots * layers * 2.0 * ctx * d * kvb
    return {
        "kernel": "paged_attention_decode",
        "backend": "pallas",
        "shapes": {"pool": f"[{layers}, blocks, {bs}, {d}] x2 ({kv_dtype})",
                   "tables": f"[{slots}, {nb}] int32",
                   "query": f"[{slots}, {window}, {d}]"},
        "flops": flops, "bytes": pool_bytes,
        # what the oracle pays on top: the dequantized logical-order
        # copy, f32, materialized (write) and consumed (read) per layer
        "gather_copy_bytes_avoided": slots * layers * 2.0 * ctx * d
        * 8.0,
        "fused_dequant": kv_dtype != "fp32",
        "context": ctx, "slots": slots, "window": window,
    }


@register_serving_kernel("moe_gate_dispatch")
def _moe_gate_dispatch_cost(spec: Dict, tokens: int = 0,
                            num_experts: int = 0, capacity: int = 0,
                            top_k: int = 1, **_) -> Dict:
    """The fused MoE gate+dispatch kernel (kernels/moe_dispatch.py):
    gate logits, softmax, top-k routing, capacity cumsum and the
    dispatch contraction in one launch.  Emits only expert_in/combine;
    `routing_bytes_avoided` is the [T, E]/[T, E, C] routing traffic the
    oracle materializes in HBM between its ~15 ops."""
    d, _, _, _, _, _, _ = _spec_dims(spec)
    T = int(tokens or spec.get("tokens") or 0)
    E = int(num_experts or spec.get("num_experts") or 0)
    C = int(capacity or max(1, int(1.25 * top_k * T / max(E, 1))))
    flops = (2.0 * T * d * E              # gate logits
             + 2.0 * T * E * C * d * top_k)  # dispatch contraction
    bytes_ = 4.0 * (T * d + d * E + E * C * d + T * E * C)
    return {
        "kernel": "moe_gate_dispatch",
        "backend": "pallas",
        "shapes": {"x": f"[{T}, {d}]", "gate_w": f"[{d}, {E}]",
                   "expert_in": f"[{E}, {C}, {d}]",
                   "combine": f"[{T}, {E}, {C}]"},
        "flops": flops, "bytes": bytes_,
        "routing_bytes_avoided": 4.0 * (T * E * C + 6.0 * T * E),
        "tokens": T, "num_experts": E, "capacity": C, "top_k": top_k,
    }


@register_serving_kernel("fused_bucket_update")
def _fused_bucket_update_cost(spec: Dict, numel: int = 0,
                              n_params: int = 1, **_) -> Dict:
    """The fused per-bucket optimizer update (kernels/fused_update.py):
    p -= lr*g over one concatenated flat bucket — the bytes are the
    same as the per-parameter chain (read p, read g, write p), the win
    is `launches_replaced` dispatches collapsing into one."""
    n = int(numel or spec.get("numel") or 0)
    return {
        "kernel": "fused_bucket_update",
        "backend": "pallas",
        "shapes": {"flat_params": f"[{n}] f32",
                   "flat_grads": f"[{n}] f32"},
        "flops": 2.0 * n, "bytes": 12.0 * n,
        "launches_replaced": int(n_params),
        "numel": n,
    }


@register_serving_kernel("paged_decode_step")
def _paged_decode_step_cost(spec: Dict, slots: int = 1,
                            context: Optional[int] = None,
                            kv_dtype: str = "fp32",
                            window: int = 1,
                            device: str = DEFAULT_DEVICE,
                            backend: str = "xla", **_) -> Dict:
    """One decode tick: `window` teacher-forced positions per slot in a
    single dispatch (window=1 is `decoder.step`, window=k+1 is the
    speculative-verify / chunked-prefill `step_window`).  Parameters
    stream from HBM ONCE per dispatch — which is why AI scales with
    slots*window and speculative decoding pays: the roofline argument,
    statically.

    `backend` picks the attention sub-cost: "xla" (default) is the
    gather composition, "pallas" the fused paged-attention kernel —
    the row then reflects what the serving-kernel tier actually
    runs."""
    d, h, layers, v, di, bs, nb = _spec_dims(spec)
    ctx = int(context if context is not None else bs * nb)
    kvb = _kv_elem_bytes(kv_dtype, bs, d)
    per_pos = layers * (8.0 * d * d + 4.0 * d * di) + 2.0 * d * v
    if backend == "pallas":
        att = serving_kernel_cost("paged_attention_decode", spec,
                                  slots=slots, context=ctx,
                                  kv_dtype=kv_dtype, window=window)
    else:
        att = serving_kernel_cost("paged_attention_gather", spec,
                                  slots=slots * window, context=ctx,
                                  kv_dtype=kv_dtype)
    flops = slots * window * per_pos + att["flops"]
    param_bytes = _lm_param_bytes(spec)
    kv_write = slots * window * layers * 2.0 * d * kvb
    act_bytes = slots * window * (d * 8.0 + v * 4.0)
    tbytes = param_bytes + att["bytes"] + kv_write + act_bytes
    ai = flops / tbytes if tbytes else 0.0
    peak, hbm = DEVICE_SPECS[device]
    return {
        "kernel": ("paged_decode_step" if window == 1
                   else f"paged_decode_step_window(W={window})"),
        "backend": backend,
        "shapes": {"tokens": f"[{slots}, {window}] int32",
                   "positions": f"[{slots}] int32",
                   "logits": f"[{slots}, {window}, {v}]"},
        "flops": flops, "bytes": tbytes,
        "param_bytes": param_bytes,
        "ai_flop_per_byte": round(ai, 2),
        "ridge_flop_per_byte": round(peak / hbm, 1),
        "bound": "memory" if ai < peak / hbm else "compute",
        "flops_per_token": flops / max(slots * window, 1),
        "slots": slots, "window": window, "kv_dtype": kv_dtype,
    }


def _resolve_decode_backend(spec: Dict, kv_dtype: str) -> str:
    """What the serving-kernel tier would actually run for this spec on
    THIS process's platform (docs/performance.md "Serving kernels") —
    so the analyze report's rows reflect reality, not aspiration.
    Best-effort: a static analyzer must never fail on registry
    absence."""
    try:
        from ..kernels import registry as kreg
        from ..kernels.paged_attention import paged_attention_supports
        import jax

        platform = jax.default_backend()
        if not kreg.kernels_armed(platform):
            return "xla"
        d, h, layers, v, di, bs, nb = _spec_dims(spec)
        reason = paged_attention_supports(
            d_model=d, n_heads=h, block_size=bs,
            max_blocks_per_seq=nb, kv_dtype=kv_dtype,
            platform=platform)
        return "xla" if reason else "pallas"
    except Exception:
        return "xla"


def analyze_generation_spec(spec: Dict, slots: Optional[int] = None,
                            kv_dtype: Optional[str] = None,
                            device: str = DEFAULT_DEVICE) -> Dict:
    """Static cost report for a generation model dir's `generation.json`
    spec: decode-step rows at window=1 and at the speculative window
    (spec_k+1 when armed), the gather-attention term, and KV-block
    sizing — everything `cli analyze MODEL_DIR` prints without building
    a decoder or compiling a step."""
    d, h, layers, v, di, bs, nb = _spec_dims(spec)
    s = int(slots or spec.get("slots") or 8)
    kd = str(kv_dtype or spec.get("kv_dtype") or "fp32")
    ctx = bs * nb
    backend = _resolve_decode_backend(spec, kd)
    rows = [serving_kernel_cost("paged_decode_step", spec, slots=s,
                                context=ctx // 2, kv_dtype=kd,
                                device=device, backend=backend)]
    spec_k = int(spec.get("spec_k") or 0)
    if spec.get("draft") or spec_k:
        rows.append(serving_kernel_cost(
            "paged_decode_step", spec, slots=s, context=ctx // 2,
            kv_dtype=kd, window=(spec_k or 4) + 1, device=device,
            backend=backend))
    if backend == "pallas":
        rows.append(serving_kernel_cost("paged_attention_decode",
                                        spec, slots=s,
                                        context=ctx // 2,
                                        kv_dtype=kd))
    rows.append(serving_kernel_cost("paged_attention_gather", spec,
                                    slots=s, context=ctx // 2,
                                    kv_dtype=kd))
    bytes_per_block = 2.0 * layers * bs * d * _kv_elem_bytes(kd, bs, d)
    return {
        "model": {"d_model": d, "n_heads": h, "n_layers": layers,
                  "vocab_size": v, "d_inner": di, "block_size": bs,
                  "max_blocks_per_seq": nb, "kv_dtype": kd, "slots": s},
        "param_bytes": _lm_param_bytes(spec),
        "bytes_per_block": bytes_per_block,
        "kernels": rows,
    }


# ---------------------------------------------------------------------------
# budget gate
# ---------------------------------------------------------------------------


def check_budget(report: Dict, budget: Dict) -> List[str]:
    """Compare one program's analyze report against its budget entry;
    returns human-readable violations (empty = within budget).

    Budget keys (all optional): `max_flops_g`, `max_hbm_traffic_gb`,
    `max_peak_hbm_gb`, `bound` ("memory"/"compute" — the verdict must
    match), `max_comm_gb` ({axis: GB} over the comm table),
    `max_unknown_ops` (cost-metadata coverage floor, default 0 when the
    key is present).  See docs/analysis.md for the file format."""
    out = []

    def over(key, actual, limit, unit="GB"):
        if limit is not None and actual > float(limit):
            out.append(f"{key}: {actual:.3f} {unit} exceeds budget "
                       f"{float(limit):.3f} {unit}")

    roof = report.get("roofline", {})
    if "max_flops_g" in budget:
        over("flops", roof.get("est_flops", 0.0) / 1e9,
             budget["max_flops_g"], "GFLOP")
    if "max_hbm_traffic_gb" in budget:
        over("hbm_traffic", roof.get("est_hbm_traffic_gb", 0.0),
             budget["max_hbm_traffic_gb"])
    if "max_peak_hbm_gb" in budget:
        over("peak_hbm", roof.get("est_peak_hbm_gb", 0.0),
             budget["max_peak_hbm_gb"])
    want_bound = budget.get("bound")
    if want_bound and roof.get("bound") and roof["bound"] != want_bound:
        out.append(f"bound verdict changed: {roof['bound']!r} "
                   f"(budget expects {want_bound!r})")
    if "max_unknown_ops" in budget:
        actual = int(roof.get("unknown_ops", 0))
        if actual > int(budget["max_unknown_ops"]):
            out.append(
                f"unknown-cost ops: {actual} exceed budget "
                f"{int(budget['max_unknown_ops'])} "
                f"(types: {roof.get('unknown_types')})")
    limits = budget.get("max_comm_gb") or {}
    comm = report.get("comm", {})
    for axis, limit in limits.items():
        actual = sum(comm.get(axis, {}).values()) / 1e9
        over(f"comm[{axis}]", actual, limit)
    return out


# ---------------------------------------------------------------------------
# analysis passes: cost-model + comm-volume
# ---------------------------------------------------------------------------


@register_pass("cost-model", order=85)
def check_cost_model(ctx):
    """Static roofline summary (info) + cost-metadata coverage: the
    per-op estimators roll up into program FLOPs, HBM traffic, AI vs
    the default device's ridge point, and the liveness-based peak-HBM
    estimate (batch assumed when feeds carry -1 dims).  Ops without
    cost metadata are reported (info) — they are excluded from the
    totals, never silently zero (docs/analysis.md)."""
    est = estimate_program(ctx.program,
                           feed_names=ctx.feed_names,
                           fetch_names=ctx.fetch_names)
    if (not est.total_flops and not est.total_bytes
            and not est.unknown_types):
        return  # startup / empty programs carry no roofline signal
    roof = est.roofline()
    if est.total_flops or est.total_bytes:
        msg = (f"static roofline (batch {est.batch_size} assumed): "
               f"{est.total_flops / 1e9:.2f} GFLOP, "
               f"{est.total_bytes / 1e9:.3f} GB traffic")
        if "ai_flop_per_byte" in roof:
            msg += (f", AI {roof['ai_flop_per_byte']} vs ridge "
                    f"{roof['ridge_flop_per_byte']} flop/B "
                    f"({est.device}) -> {roof['bound']}-bound")
        msg += f"; est peak HBM {roof['est_peak_hbm_gb']} GB"
        yield ctx.diag("info", msg, ctx.program.blocks[0])
    if est.unknown_types:
        kinds = ", ".join(f"{t} x{c}"
                          for t, c in sorted(est.unknown_types.items()))
        yield ctx.diag(
            "info",
            f"{sum(est.unknown_types.values())} op(s) have no cost "
            f"metadata and are excluded from the totals: {kinds}",
            ctx.program.blocks[0],
            hint="register metadata via core.registry.register_op_cost "
                 "(or cost= on register_op) so the roofline covers them")


@register_pass("comm-volume", order=86)
def check_comm_volume(ctx):
    """Quantified communication volume (info): per-mesh-axis bytes
    all-reduced / gathered / resharded, from explicit collectives, the
    gradient-sync payload, and the sharding propagation's pending psums
    + resharding hotspots — the byte counts behind the qualitative
    `sharding-consistency` warnings.  Programs with no mesh, no
    annotations, and no collective/send ops skip the pass."""
    from ..parallel.spmd import has_annotations

    program = ctx.program
    block = program.global_block()
    has_coll = any(op.type in _COLLECTIVE_KIND or op.type == "send"
                   for blk in program.blocks for op in blk.ops)
    if (not program.mesh_axes and not has_annotations(block)
            and not has_coll):
        return
    est = estimate_comm(program, fetch_names=ctx.fetch_names)
    for axis, kinds in sorted(est.by_axis().items()):
        detail = ", ".join(f"{k} {b / 1e6:.3f} MB"
                           for k, b in sorted(kinds.items()))
        yield ctx.diag(
            "info",
            f"comm volume over {axis!r} per step: {detail}",
            block)


_install_kind_table()
