"""Shared diagnostic model for the static program verifier.

The reference framework validates op descs at compile time through
proto-level checks plus per-op `InferShape` asserts scattered through C++
(framework.proto OpDesc/VarDesc, operator.cc InferShapeContext) — errors
surface as one-off PADDLE_ENFORCE aborts.  Here every analysis pass emits
structured `Diagnostic` records instead, so one verification run can
report ALL problems in a program at once, callers can filter by severity,
and tools (cli verify, debugger dumps, Executor pre-flight) share the
same machinery.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

__all__ = [
    "Diagnostic",
    "ProgramVerificationError",
    "SEVERITIES",
    "severity_rank",
    "format_diagnostics",
    "max_severity",
]

# ordered weakest -> strongest; rank comparisons use list position
SEVERITIES = ("info", "warning", "error")


def severity_rank(severity: str) -> int:
    try:
        return SEVERITIES.index(severity)
    except ValueError:
        raise ValueError(
            f"unknown severity {severity!r}; expected one of {SEVERITIES}"
        ) from None


@dataclasses.dataclass
class Diagnostic:
    """One finding from one analysis pass.

    `block_idx` / `op_idx` locate the offending op in the Program IR
    (`op_idx` is None for block- or program-level findings); `op_repr` is
    a short human rendering of the op desc; `hint` suggests a fix.
    """

    pass_id: str
    severity: str  # "error" | "warning" | "info"
    message: str
    block_idx: int = 0
    op_idx: Optional[int] = None
    op_type: Optional[str] = None
    op_repr: str = ""
    hint: str = ""
    # source-level location (the concurrency analyzer locates findings
    # in repo files, not Program blocks); when `file` is set it wins
    # over the block/op rendering
    file: Optional[str] = None
    line: Optional[int] = None

    def __post_init__(self):
        severity_rank(self.severity)  # validate

    def to_dict(self) -> dict:
        """Machine-readable form (cli verify/analyze/concurrency
        --json): severity + pass id, a structured location, the
        message, and the fix hint — stable keys for CI annotations and
        editor integrations."""
        loc: dict = {
            "block": self.block_idx,
            "op": self.op_idx,
            "op_type": self.op_type,
        }
        if self.file is not None:
            loc = {"file": self.file, "line": self.line}
        return {
            "pass": self.pass_id,
            "severity": self.severity,
            "message": self.message,
            "location": loc,
            "hint": self.hint or None,
        }

    def location(self) -> str:
        if self.file is not None:
            return f"{self.file}:{self.line}"
        loc = f"block {self.block_idx}"
        if self.op_idx is not None:
            loc += f" op {self.op_idx}"
            if self.op_type:
                loc += f" ({self.op_type})"
        return loc

    def __str__(self):
        s = f"[{self.severity}] {self.pass_id}: {self.message} " \
            f"({self.location()})"
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s


def max_severity(diagnostics: List[Diagnostic]) -> Optional[str]:
    if not diagnostics:
        return None
    return max(diagnostics, key=lambda d: severity_rank(d.severity)).severity


def format_diagnostics(diagnostics: List[Diagnostic]) -> str:
    """Multi-line report, strongest severity first, stable within severity."""
    ordered = sorted(
        diagnostics,
        key=lambda d: (-severity_rank(d.severity), d.block_idx,
                       -1 if d.op_idx is None else d.op_idx),
    )
    counts = {}
    for d in diagnostics:
        counts[d.severity] = counts.get(d.severity, 0) + 1
    head = ", ".join(
        f"{counts[s]} {s}{'s' if counts[s] != 1 else ''}"
        for s in reversed(SEVERITIES) if s in counts
    ) or "no findings"
    return "\n".join([f"program verification: {head}"]
                     + [str(d) for d in ordered])


class ProgramVerificationError(ValueError):
    """Raised by Program.verify / the Executor pre-flight when a program
    has diagnostics at or above the requested severity level."""

    def __init__(self, diagnostics: List[Diagnostic]):
        self.diagnostics = list(diagnostics)
        super().__init__(format_diagnostics(self.diagnostics))
