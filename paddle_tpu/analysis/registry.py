"""Pass registry + the verification driver.

Analysis passes are plain functions `fn(ctx) -> iterable[Diagnostic]`
registered under a stable pass id.  `verify_program` runs a pass
pipeline over one Program and collects every diagnostic — the pass-based
architecture mirrors the reference's compile-time pipeline (one
InferShape/validate hook per op desc), but passes here see the WHOLE
program so they can check cross-op and cross-block invariants the
per-op hooks could not.

Registering a custom pass:

    from paddle_tpu import analysis

    @analysis.register_pass("my-invariant")
    def my_invariant(ctx):
        for block, idx, op in ctx.iter_ops():
            if bad(op):
                yield ctx.diag("error", "...", block, idx, op,
                               hint="...")
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from ..core import registry as op_registry
from .diagnostics import (
    Diagnostic,
    ProgramVerificationError,
    severity_rank,
)

__all__ = [
    "AnalysisPass",
    "PassContext",
    "register_pass",
    "registered_passes",
    "get_pass",
    "verify_program",
]


@dataclasses.dataclass
class AnalysisPass:
    id: str
    fn: Callable  # fn(ctx) -> iterable[Diagnostic]
    order: int = 100  # lower runs first (shape prop feeds later passes)
    doc: str = ""


_PASSES: Dict[str, AnalysisPass] = {}


def register_pass(pass_id: str, order: int = 100):
    """Decorator: register `fn(ctx)` as analysis pass `pass_id`."""

    def deco(fn):
        _PASSES[pass_id] = AnalysisPass(
            id=pass_id, fn=fn, order=order, doc=(fn.__doc__ or "").strip()
        )
        return fn

    return deco


def registered_passes() -> List[AnalysisPass]:
    return sorted(_PASSES.values(), key=lambda p: (p.order, p.id))


def get_pass(pass_id: str) -> AnalysisPass:
    if pass_id not in _PASSES:
        raise KeyError(
            f"analysis pass {pass_id!r} is not registered; known: "
            f"{sorted(_PASSES)}"
        )
    return _PASSES[pass_id]


class PassContext:
    """Per-verification state shared by every pass.

    `feed_names` / `fetch_names` are optional runtime context (the
    Executor pre-flight knows them; `Program.verify()` usually does not)
    — passes must degrade severity gracefully when they are None.
    """

    def __init__(self, program, feed_names=None, fetch_names=None):
        self.program = program
        self.feed_names = (None if feed_names is None
                           else {str(n) for n in feed_names})
        self.fetch_names = (None if fetch_names is None
                            else {str(n) for n in fetch_names})

    # -- iteration helpers ---------------------------------------------------
    def iter_ops(self):
        """Yield (block, op_idx, op) over every block in program order."""
        for block in self.program.blocks:
            for idx, op in enumerate(block.ops):
                yield block, idx, op

    def op_info(self, op):
        """Registered OpInfo for `op`, or None when unregistered.  For a
        generic grad op this resolves to the FORWARD op's info (the
        registry convention) — callers compare info.type vs op.type."""
        try:
            return op_registry.get_op_info(op.type)
        except KeyError:
            return None

    def resolvable(self, block, name: str) -> bool:
        """Scope-style lookup: name found in `block` or an ancestor."""
        b = block
        seen = set()
        while b is not None and b.idx not in seen:
            seen.add(b.idx)
            if name in b.vars:
                return True
            b = b.parent if 0 <= b.parent_idx < len(self.program.blocks) \
                else None
        return False

    # -- diagnostic factory --------------------------------------------------
    def diag(self, severity, message, block=None, op_idx=None, op=None,
             pass_id="", hint="") -> Diagnostic:
        return Diagnostic(
            pass_id=pass_id,
            severity=severity,
            message=message,
            block_idx=getattr(block, "idx", 0) if block is not None else 0,
            op_idx=op_idx,
            op_type=getattr(op, "type", None),
            op_repr=repr(op) if op is not None else "",
            hint=hint,
        )


def verify_program(
    program,
    level: Optional[str] = None,
    passes: Optional[Sequence[str]] = None,
    feed_names: Optional[Iterable[str]] = None,
    fetch_names: Optional[Iterable[str]] = None,
) -> List[Diagnostic]:
    """Run analysis passes over `program`, returning every diagnostic.

    `level`: when set ("info" | "warning"/"warn" | "error"), raise
    ProgramVerificationError if any diagnostic is at or above that
    severity.  None/"off" never raises.
    `passes`: restrict to these pass ids (default: all registered).
    """
    from . import passes as _builtin  # noqa: F401  (registers built-ins)
    from . import cost_model as _cost  # noqa: F401  (cost/comm passes)

    selected = (registered_passes() if passes is None
                else [get_pass(p) for p in passes])
    ctx = PassContext(program, feed_names=feed_names,
                      fetch_names=fetch_names)
    diagnostics: List[Diagnostic] = []
    for p in selected:
        for d in p.fn(ctx) or ():
            if not d.pass_id:
                d.pass_id = p.id
            diagnostics.append(d)
    if level not in (None, "off"):
        lvl = "warning" if level == "warn" else level
        threshold = severity_rank(lvl)
        bad = [d for d in diagnostics
               if severity_rank(d.severity) >= threshold]
        if bad:
            raise ProgramVerificationError(bad)
    return diagnostics
