"""Whole-repo AST concurrency analyzer: lock-order / race / hygiene lint.

The production core of this framework is its threaded runtimes — the
pserver wire protocol (parallel/pserver.py), the per-endpoint comm
workers (parallel/comm.py), the elastic controller (cloud/cluster.py),
the serving scheduler (serving/generation.py), the prefetch pipeline
(reader/pipeline.py) — and every PR's review log is dominated by the
same hand-caught bug classes: lock-order inversions, blocking calls made
with a lock held, attributes shared across threads without their lock,
and thread-lifecycle leaks.  This module automates that reviewer as a
SOURCE-level analysis (no imports, no execution — plain `ast`), the
concurrency sibling of the Program-IR passes in passes.py/cost_model.py.

Rule catalog (docs/analysis.md "Concurrency analysis"):

  ``lock-order``        [error]  the inter-lock acquisition-order graph
      (edge A->B when B is acquired — directly or through an intra-class
      call chain — while A is held) must be acyclic; a cycle is a static
      deadlock.  Nested acquisition of the SAME non-reentrant Lock /
      Condition is a self-deadlock (error when syntactically nested,
      warning when reached through a call chain, which may be guarded by
      state the analysis cannot see).
  ``blocking-under-lock`` [error]  no blocking call while holding a
      lock: raw socket send*/recv* and the pserver frame helpers (the
      old tools/lint.py rule 4, which now delegates here), plus
      `Thread.join`, blocking `Queue.get/put`, `subprocess` calls,
      `time.sleep`, and waiting on a Condition/Event OTHER than the one
      (sole lock) being held — one stalled peer convoys every thread
      behind the lock, and waiting on B while holding A is the classic
      lost-wakeup/deadlock shape.  The per-endpoint worker pattern
      (`*conn_lock`/`*ep_lock`/`*endpoint_lock` names) stays allowlisted
      for the socket family, exactly as rule 4 had it.
  ``unguarded-attr``    [warning]  RacerD-style ownership inference: an
      instance attribute WRITTEN under a lock in one method but accessed
      with no lock in a method reachable from a different thread
      entrypoint is a data race candidate.  Plain bool/None flag writes
      (`self._stop = True`) demote to info — the CPython store is
      atomic and the pattern is idiomatic for cooperative shutdown.
  ``thread-join``       [error]  a non-daemon `threading.Thread` that is
      never `.join()`ed anywhere in its file keeps the process alive
      after main exits.
  ``thread-start-order`` [error]  `self.<t>.start()` before an
      attribute the thread's target reads is first assigned (in the
      same function body): the thread can observe the attribute missing.

Suppression convention (mirrors lint rule 4): put
``# lint: <rule>-ok`` — e.g. ``# lint: lock-order-ok`` or
``# lint: blocking-under-lock-ok`` — with a rationale on the flagged
line or on the `with` line whose lock scope contains it; the finding
demotes to info and does not gate CI.  ``# lint: send-under-lock-ok``
is honored as a legacy alias for the socket family.

Entry points:
  * `analyze_source(src, filename)` — one source string (tests/fixtures);
  * `analyze_paths(paths)` — files/dirs, whole-`paddle_tpu` by default;
  * `to_diagnostics(findings)` — the PR 3 Diagnostic model (file/line
    carried in the new source-location fields);
  * `python -m paddle_tpu.cli concurrency [--json]` — the CLI surface;
  * tools/lint.py rule 4 file-loads this module standalone (no package
    import), so module scope here must stay stdlib-only.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "RULES",
    "analyze_source",
    "analyze_file",
    "analyze_paths",
    "to_diagnostics",
    "DEFAULT_PATHS",
]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_PATHS = (os.path.join(_REPO_ROOT, "paddle_tpu"),)

RULES = ("lock-order", "blocking-under-lock", "unguarded-attr",
         "thread-join", "thread-start-order")

# threading constructors -> primitive kind.  "reentrant" kinds may be
# re-acquired by the holder; everything else self-deadlocks.
_PRIMITIVE_KINDS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Event": "event",
    "Semaphore": "semaphore",
    "BoundedSemaphore": "semaphore",
    "Barrier": "barrier",
}
_LOCKISH_KINDS = ("lock", "rlock", "condition")
_REENTRANT = ("rlock",)

_QUEUE_CTORS = ("Queue", "LifoQueue", "PriorityQueue", "SimpleQueue")

# rule 4's socket family, verbatim (tools/lint.py delegates here)
_SOCKET_BLOCKING = frozenset(
    "send sendall sendmsg sendto recv recv_into recvfrom recvmsg "
    "_send_frame _send_frame_parts _recv_frame _read_exact "
    "_sendall_parts".split())
_SUBPROCESS_BLOCKING = frozenset(
    "run call check_call check_output communicate".split())
_PER_ENDPOINT_LOCK = ("conn_lock", "ep_lock", "endpoint_lock")

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*([a-z-]+)-ok")


@dataclasses.dataclass
class Finding:
    """One concurrency finding, located at source level (unlike the
    Program-IR Diagnostic, which locates by block/op)."""

    rule: str            # one of RULES
    severity: str        # "error" | "warning" | "info"
    file: str            # path as given to the analyzer
    line: int
    message: str
    hint: str = ""
    suppressed: bool = False   # a `# lint: <rule>-ok` comment demoted it

    def __str__(self):
        tag = " (suppressed)" if self.suppressed else ""
        return (f"{self.file}:{self.line}: [{self.severity}] "
                f"{self.rule}: {self.message}{tag}")


# ---------------------------------------------------------------------------
# per-file model extraction
# ---------------------------------------------------------------------------


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted rendering of an attribute chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _primitive_kind(value: ast.AST) -> Optional[str]:
    """threading.Lock() / Condition(...) / queue.Queue() -> kind."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    if name in _PRIMITIVE_KINDS:
        return _PRIMITIVE_KINDS[name]
    if name in _QUEUE_CTORS:
        return "queue"
    if name == "Thread":
        return "thread"
    if name == "ThreadPoolExecutor":
        return "executor"
    return None


def _thread_target(call: ast.Call) -> Optional[str]:
    """`Thread(target=self.m, ...)` -> "m" (self-method targets only)."""
    for kw in call.keywords:
        if kw.arg == "target":
            t = kw.value
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                return t.attr
    return None


def _thread_daemon(call: ast.Call) -> Optional[bool]:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return None


@dataclasses.dataclass
class _Access:
    attr: str
    is_write: bool
    held: frozenset      # lock ids held at the access
    line: int
    flag_write: bool = False   # write of a bool/None constant


@dataclasses.dataclass
class _Acquire:
    lock: str            # lock id
    held_before: frozenset
    line: int
    with_line: int       # header line of the with-statement


@dataclasses.dataclass
class _BlockingCall:
    kind: str            # "socket"|"join"|"queue"|"subprocess"|"sleep"|"wait"
    name: str
    receiver: str        # dotted receiver ("" when none)
    held: frozenset
    line: int
    with_lines: Tuple[int, ...]   # header lines of the enclosing lockish withs


@dataclasses.dataclass
class _Call:
    method: str          # self.<method> intra-class call
    held: frozenset
    line: int
    with_lines: Tuple[int, ...]


@dataclasses.dataclass
class _ThreadDecl:
    name: str            # "self._worker" or local name
    target: Optional[str]
    daemon: Optional[bool]
    line: int
    started_line: Optional[int] = None
    joined: bool = False


class _MethodModel:
    def __init__(self, name: str):
        self.name = name
        self.accesses: List[_Access] = []
        self.acquires: List[_Acquire] = []
        self.blocking: List[_BlockingCall] = []
        self.calls: List[_Call] = []
        self.stmt_events: List[Tuple[int, str, str]] = []  # start-order


class _ClassModel:
    def __init__(self, name: str):
        self.name = name
        self.locks: Dict[str, Tuple[str, int]] = {}    # attr -> (kind, line)
        self.threads: Dict[str, _ThreadDecl] = {}      # attr/local key
        self.methods: Dict[str, _MethodModel] = {}
        self.thread_targets: Set[str] = set()          # self-method names


def _lock_id(cls: Optional[_ClassModel], module_locks: Dict[str, str],
             expr: ast.AST, scope: str = "") -> Optional[str]:
    """Resolve a with-context expression to a lock identity string, or
    None when it is not a known/lockish primitive.

    Identities: "Class.attr" for `self._x`, "<module>.name" for
    globals, "<local:Class.method>.name" for function locals that
    merely LOOK like locks (the rule-4 name heuristic keeps working on
    code whose constructor the file never shows).  Locals are scoped
    PER FUNCTION: two functions' same-named locals are different
    objects and must not forge cross-function ordering edges."""
    name = _dotted(expr)
    if name.startswith("self.") and name.count(".") == 1 and cls:
        attr = name.split(".", 1)[1]
        kind = cls.locks.get(attr, (None, 0))[0]
        if kind in _LOCKISH_KINDS:
            return f"{cls.name}.{attr}"
        if kind is not None:
            return None   # known non-lock primitive (event/queue/...)
        if _looks_lockish(attr):
            return f"{cls.name}.{attr}"
        return None
    if name and "." not in name:
        if module_locks.get(name) in _LOCKISH_KINDS:
            return f"<module>.{name}"
        if name in module_locks:
            return None
        if _looks_lockish(name):
            return f"<local:{scope}>.{name}"
        return None
    # dotted non-self expression (other.lock): use the name heuristic
    if name and _looks_lockish(name.rsplit(".", 1)[-1]):
        return f"<other>.{name}"
    return None


def _looks_lockish(name: str) -> bool:
    parts = [p for p in re.split(r"[^a-z]+", name.lower()) if p]
    if any(p in ("lock", "cond", "cv", "mutex") for p in parts):
        return True
    return name.lower().endswith(("lock", "cond"))


def _is_per_endpoint(lock_id: str) -> bool:
    return lock_id.rsplit(".", 1)[-1].lower().endswith(_PER_ENDPOINT_LOCK)


class _FunctionScanner(ast.NodeVisitor):
    """Walk one function body tracking the stack of held locks; record
    attribute accesses, lock acquisitions, intra-class calls, blocking
    calls, and thread starts/joins.  Nested def/lambda bodies are code
    that runs LATER (after the lock is released) — not descended with
    the held set; they are scanned separately with an empty stack."""

    def __init__(self, owner: "_FileScanner", cls: Optional[_ClassModel],
                 model: _MethodModel):
        self.owner = owner
        self.cls = cls
        self.model = model
        self.held: List[str] = []
        self.with_lines: List[int] = []
        self._local_threads: Dict[str, _ThreadDecl] = {}

    # -- helpers ------------------------------------------------------------
    def _heldset(self) -> frozenset:
        return frozenset(self.held)

    def _thread_decl_for(self, dotted: str) -> Optional[_ThreadDecl]:
        if self.cls and dotted.startswith("self."):
            return self.cls.threads.get(dotted)
        return self._local_threads.get(dotted)

    # -- statements ----------------------------------------------------------
    def _scope_tag(self) -> str:
        return f"{self.cls.name if self.cls else ''}.{self.model.name}"

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            # context expressions are EVALUATED (with the previous
            # items' locks already held) — calls inside them must feed
            # the blocking/call-chain analyses like any other code
            self.visit(item.context_expr)
            lid = _lock_id(self.cls, self.owner.module_locks,
                           item.context_expr, self._scope_tag())
            if lid is not None:
                # `with a, b:` acquires left-to-right: a is already
                # held when b is taken, so record-then-extend per item
                self.model.acquires.append(_Acquire(
                    lid, self._heldset(), item.context_expr.lineno
                    if hasattr(item.context_expr, "lineno")
                    else node.lineno, node.lineno))
                acquired.append(lid)
                self.held.append(lid)
        if acquired:
            self.with_lines.append(node.lineno)
        for stmt in node.body:
            self.visit(stmt)
        if acquired:
            self.with_lines.pop()
        # drop OUR acquisitions specifically (a manual x.acquire() in
        # the body may have interleaved entries onto the held stack)
        for lid in reversed(acquired):
            self._drop_held(lid)

    def visit_FunctionDef(self, node):
        # nested def: its body runs AFTER the enclosing lock scope, on
        # whoever calls it — scan it as its own (uncallable-by-name)
        # model so its blocking calls neither read as under-lock nor
        # mark the ENCLOSING method as a blocking helper
        sub = _MethodModel(f"{self.model.name}.<locals>.{node.name}")
        owner_cls = self.cls
        if owner_cls is None:
            owner_cls = self.owner.classes.setdefault(
                "<module-fns>", _ClassModel("<module-fns>"))
        owner_cls.methods[sub.name] = sub
        sc = _FunctionScanner(self.owner, self.cls, sub)
        for stmt in node.body:
            sc.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass   # same reasoning; lambda bodies are expression-only

    def visit_Assign(self, node: ast.Assign):
        for tgt in node.targets:
            self._record_target(tgt, node.value)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record_target(node.target, None, aug=True)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._record_target(node.target, node.value)
            self.visit(node.value)

    def _record_target(self, tgt: ast.AST, value: Optional[ast.AST],
                       aug: bool = False):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._record_target(e, None)
            return
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self" and self.cls is not None):
            kind = _primitive_kind(value) if value is not None else None
            if kind in ("thread",):
                decl = _ThreadDecl(f"self.{tgt.attr}",
                                   _thread_target(value),
                                   _thread_daemon(value), tgt.lineno)
                self.cls.threads[f"self.{tgt.attr}"] = decl
                t = _thread_target(value)
                if t:
                    self.cls.thread_targets.add(t)
            elif kind is not None:
                self.cls.locks.setdefault(tgt.attr, (kind, tgt.lineno))
            else:
                flag = (isinstance(value, ast.Constant)
                        and (value.value is None
                             or isinstance(value.value, bool)))
                self.model.accesses.append(_Access(
                    tgt.attr, True, self._heldset(), tgt.lineno,
                    flag_write=flag and not aug))
            # `self.t.daemon = True` handled via Attribute-of-Attribute
        elif isinstance(tgt, ast.Name) and value is not None:
            kind = _primitive_kind(value)
            if kind == "thread":
                decl = _ThreadDecl(tgt.id, _thread_target(value),
                                   _thread_daemon(value), tgt.lineno)
                self._local_threads[tgt.id] = decl
                # local threads share the never-joined check
                self.owner.local_threads.append(decl)
                t = _thread_target(value)
                if t and self.cls is not None:
                    self.cls.thread_targets.add(t)
        elif isinstance(tgt, ast.Subscript):
            # container mutation (`self._m[k] = v`): a WRITE to the
            # underlying attribute for the race analysis — shared
            # dict/list state is the common shape in this repo
            self._record_root_write(tgt.value)
            self.visit(tgt.slice)
        elif (isinstance(tgt, ast.Attribute) and tgt.attr == "daemon"):
            decl = self._thread_decl_for(_dotted(tgt.value))
            if decl is not None and isinstance(value, ast.Constant):
                decl.daemon = bool(value.value)
        elif isinstance(tgt, ast.Attribute):
            # `self.a.b = v` mutates the object held in self.a
            self._record_root_write(tgt.value)

    def _record_root_write(self, node: ast.AST):
        """Record the `self.<attr>` root of a mutation-target chain
        (subscripts/attributes) as a write access."""
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and self.cls is not None):
                self.model.accesses.append(_Access(
                    node.attr, True, self._heldset(), node.lineno))
                return
            node = node.value

    # -- expressions ----------------------------------------------------------
    def visit_Attribute(self, node: ast.Attribute):
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and isinstance(node.ctx, ast.Load)
                and self.cls is not None):
            self.model.accesses.append(_Access(
                node.attr, False, self._heldset(), node.lineno))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        name = _call_name(node)
        recv = (_dotted(node.func.value)
                if isinstance(node.func, ast.Attribute) else "")
        held = self._heldset()
        wl = tuple(self.with_lines)

        if (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self" and self.cls is not None):
            self.model.calls.append(_Call(name, held, node.lineno, wl))
        elif isinstance(node.func, ast.Name):
            # bare-name call: may hit a module-level helper — recorded
            # for the one-hop transitive blocking check
            self.model.calls.append(_Call(name, held, node.lineno, wl))

        decl = self._thread_decl_for(recv) if recv else None
        if name == "start" and decl is not None:
            decl.started_line = node.lineno
            self.model.stmt_events.append((node.lineno, "start", recv))
        if name == "join":
            if decl is not None:
                decl.joined = True
            # `self.X.join()` may join a thread declared in another
            # method of the same class — resolve lazily at report time
            self.owner.joined_names.add(recv)

        # explicit lock.acquire()/release(): linear-scan tracking so
        # manually-managed locks contribute ordering edges and a held
        # set just like `with` statements (conservative: a conditional
        # acquire counts as held through the rest of the function)
        if name in ("acquire", "release") \
                and isinstance(node.func, ast.Attribute):
            lid = _lock_id(self.cls, self.owner.module_locks,
                           node.func.value, self._scope_tag())
            if lid is not None:
                if name == "acquire":
                    self.model.acquires.append(_Acquire(
                        lid, self._heldset(), node.lineno,
                        node.lineno))
                    self.held.append(lid)
                else:
                    self._drop_held(lid)

        # record blocking-class calls even with NO lock held: the
        # one-hop transitive check needs to know which helpers block
        self._classify_blocking(node, name, recv, held, wl)
        self.generic_visit(node)

    def _drop_held(self, lid: str):
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i] == lid:
                del self.held[i]
                return

    def _classify_blocking(self, node: ast.Call, name: str, recv: str,
                           held: frozenset, wl: Tuple[int, ...]):
        add = self.model.blocking.append
        if name in _SOCKET_BLOCKING:
            add(_BlockingCall("socket", name, recv, held, node.lineno, wl))
            return
        if name == "sleep" and recv in ("time", ""):
            add(_BlockingCall("sleep", name, recv, held, node.lineno, wl))
            return
        if recv == "subprocess" and (name in _SUBPROCESS_BLOCKING
                                     or name == "Popen"):
            add(_BlockingCall("subprocess", name, recv, held,
                              node.lineno, wl))
            return
        if name in ("wait", "communicate") and recv.startswith(
                "subprocess."):
            add(_BlockingCall("subprocess", name, recv, held,
                              node.lineno, wl))
            return
        if name == "join":
            decl = self._thread_decl_for(recv) if recv else None
            known_thread = decl is not None or (
                self.cls is not None and recv in self.cls.threads)
            if known_thread:
                add(_BlockingCall("join", name, recv, held,
                                  node.lineno, wl))
            return
        if name in ("get", "put") and self._is_known_queue(recv):
            if not _nonblocking_kwargs(node, name):
                add(_BlockingCall("queue", name, recv, held,
                                  node.lineno, wl))
            return
        if name in ("wait", "wait_for"):
            lid = _lock_id(self.cls, self.owner.module_locks,
                           node.func.value, self._scope_tag()) \
                if isinstance(node.func, ast.Attribute) else None
            kind = self._primitive_kind_of(recv)
            if kind == "event" or (lid is not None and lid in held
                                   and len(held) > 1):
                # waiting on an Event with ANY lock held, or on the
                # held condition while ALSO holding another lock:
                # the other lock stays held for the whole wait
                add(_BlockingCall("wait", name, recv, held,
                                  node.lineno, wl))
            elif (kind == "condition" and lid is not None
                  and lid not in held):
                # waiting on a condition NOT held -> runtime error
                # anyway, but flag it as blocking misuse
                add(_BlockingCall("wait", name, recv, held,
                                  node.lineno, wl))

    def _primitive_kind_of(self, recv: str) -> Optional[str]:
        if recv.startswith("self.") and self.cls is not None:
            return self.cls.locks.get(recv.split(".", 1)[1],
                                      (None, 0))[0]
        if recv in self.owner.module_locks:
            return self.owner.module_locks[recv]
        return None

    def _is_known_queue(self, recv: str) -> bool:
        return self._primitive_kind_of(recv) == "queue"


def _nonblocking_kwargs(node: ast.Call, method: str = "get") -> bool:
    for kw in node.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
        if kw.arg == "timeout":
            # bounded wait: convoy is time-boxed — but an explicit
            # timeout=None is the infinite default spelled out
            if not (isinstance(kw.value, ast.Constant)
                    and kw.value.value is None):
                return True
    # positional block=False: q.get(False) / q.put(item, False) —
    # put's first positional is the ITEM, its block flag is second
    block_pos = 1 if method == "put" else 0
    if len(node.args) > block_pos \
            and isinstance(node.args[block_pos], ast.Constant) \
            and node.args[block_pos].value is False:
        return True
    return False


class _FileScanner:
    """Extract the concurrency model of one source file."""

    def __init__(self, tree: ast.AST, path: str, source: str):
        self.path = path
        self.lines = source.splitlines()
        self.module_locks: Dict[str, str] = {}
        self.classes: Dict[str, _ClassModel] = {}
        self.local_threads: List[_ThreadDecl] = []
        self.joined_names: Set[str] = set()
        self.module_model = _MethodModel("<module>")

        for node in tree.body:
            self._scan_top(node)

    def _scan_top(self, node: ast.AST):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            kind = _primitive_kind(node.value)
            if kind is not None:
                self.module_locks[node.targets[0].id] = kind
        if isinstance(node, ast.ClassDef):
            cls = _ClassModel(node.name)
            self.classes[node.name] = cls
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    self.scan_function(cls, sub)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.scan_function(None, node)
        else:
            # module-level statements (incl. if __main__ blocks)
            sc = _FunctionScanner(self, None, self.module_model)
            sc.visit(node)

    def scan_function(self, cls: Optional[_ClassModel], node):
        model = _MethodModel(node.name)
        if cls is not None:
            cls.methods[node.name] = model
        else:
            # module-level functions live in a synthetic class so
            # the rule checkers traverse one uniform shape
            self.classes.setdefault("<module-fns>",
                                    _ClassModel("<module-fns>"))
            self.classes["<module-fns>"].methods[node.name] = model
        sc = _FunctionScanner(self, cls, model)
        for stmt in node.body:
            sc.visit(stmt)

    def suppressed(self, rule: str, *lines: int) -> bool:
        """`# lint: <rule>-ok` on any of the given source lines, or on
        a pure-comment line block immediately above one of them."""
        aliases = {rule}
        if rule == "blocking-under-lock":
            aliases.add("send-under-lock")

        def match(ln: int) -> bool:
            if not 0 < ln <= len(self.lines):
                return False
            return any(m.group(1) in aliases
                       for m in _SUPPRESS_RE.finditer(self.lines[ln - 1]))

        for ln in lines:
            if match(ln):
                return True
            above = ln - 1
            while (0 < above <= len(self.lines)
                   and self.lines[above - 1].lstrip().startswith("#")):
                if match(above):
                    return True
                above -= 1
        return False


# ---------------------------------------------------------------------------
# rule evaluation
# ---------------------------------------------------------------------------


def _method_acquires(cls: _ClassModel) -> Dict[str, Set[str]]:
    """Fixed point: locks each method may acquire, directly or through
    intra-class calls."""
    acq = {m: {a.lock for a in mm.acquires}
           for m, mm in cls.methods.items()}
    changed = True
    while changed:
        changed = False
        for m, mm in cls.methods.items():
            for c in mm.calls:
                extra = acq.get(c.method)
                if extra and not extra <= acq[m]:
                    acq[m] |= extra
                    changed = True
    return acq


def _check_lock_order(sc: _FileScanner, findings: List[Finding]):
    # edges: (held_lock, acquired_lock) -> first evidence site
    edges: Dict[Tuple[str, str], Tuple[int, int, str]] = {}
    kinds: Dict[str, str] = {f"<module>.{n}": k
                             for n, k in sc.module_locks.items()}

    for cls in sc.classes.values():
        for attr, (kind, _ln) in cls.locks.items():
            kinds[f"{cls.name}.{attr}"] = kind
        acq = _method_acquires(cls)
        for mname, mm in cls.methods.items():
            for a in mm.acquires:
                for h in a.held_before:
                    if h == a.lock:
                        # direct nested re-acquisition of one lock
                        if kinds.get(h, "lock") not in _REENTRANT:
                            sup = sc.suppressed("lock-order", a.line,
                                                a.with_line)
                            findings.append(Finding(
                                "lock-order",
                                "info" if sup else "error",
                                sc.path, a.line,
                                f"nested acquisition of non-reentrant "
                                f"{h} ({kinds.get(h, 'lock')}) — "
                                "self-deadlock",
                                hint="use an RLock, or split the "
                                "locked region so the inner with is "
                                "not reached with the lock held",
                                suppressed=sup))
                    else:
                        edges.setdefault(
                            (h, a.lock),
                            (a.line, a.with_line, cls.name))
            # call-through acquisition: calling m2 (which acquires B)
            # while holding A
            for c in mm.calls:
                for b in acq.get(c.method, ()):
                    for h in c.held:
                        if h == b:
                            if kinds.get(h, "lock") not in _REENTRANT:
                                sup = sc.suppressed(
                                    "lock-order", c.line, *c.with_lines)
                                findings.append(Finding(
                                    "lock-order",
                                    "info" if sup else "warning",
                                    sc.path, c.line,
                                    f"call to self.{c.method}() while "
                                    f"holding {h}, which it "
                                    "re-acquires — self-deadlock if "
                                    "this path runs",
                                    hint="add a *_locked variant that "
                                    "assumes the lock, or release "
                                    "before the call",
                                    suppressed=sup))
                        else:
                            edges.setdefault(
                                (h, b), (c.line, c.line, cls.name))

    # cycle detection over the inter-lock graph
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    for cycle in _find_cycles(graph):
        # evidence: one edge of the cycle (the lexically first)
        pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
        sites = [edges[p] for p in pairs if p in edges]
        line, with_line, _cls = min(sites) if sites else (0, 0, "")
        sup = any(sc.suppressed("lock-order", s[0], s[1])
                  for s in sites)
        findings.append(Finding(
            "lock-order", "info" if sup else "error", sc.path, line,
            "lock-order cycle: " + " -> ".join(cycle + [cycle[0]])
            + " — two threads taking these locks in different orders "
            "deadlock",
            hint="pick one global order (document it in the class "
            "docstring) and re-acquire in that order, or collapse to "
            "one lock; a deliberate ordering-safe design can be "
            "annotated `# lint: lock-order-ok` with a rationale",
            suppressed=sup))


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles via DFS over SCCs (small graphs; Tarjan then a
    simple walk per SCC is plenty)."""
    index = {}
    low = {}
    stack: List[str] = []
    on = set()
    sccs = []
    counter = [0]

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in graph.get(v, ()):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def _check_blocking(sc: _FileScanner, findings: List[Finding]):
    hints = {
        "socket": "move the IO outside the lock (snapshot under it, "
        "send after), or use a per-endpoint `*_conn_lock`",
        "join": "set a deadline and join outside the lock — the "
        "joined thread may need this very lock to finish",
        "queue": "use get_nowait/put_nowait or a timeout, or move the "
        "queue op outside the lock",
        "subprocess": "run the subprocess outside the lock; keep only "
        "the state update under it",
        "sleep": "sleep outside the lock, or use cond.wait(timeout) "
        "so waiters can be woken",
        "wait": "wait only on the lock you hold: release other locks "
        "first (waiting on B while holding A is the lost-wakeup/"
        "deadlock shape)",
    }
    # which functions/methods make a DIRECT blocking call anywhere in
    # their body (for the one-hop transitive check below)
    fn_blocks: Dict[Tuple[str, str], Set[str]] = {}
    all_models: List[Tuple[str, _MethodModel]] = [
        ("", sc.module_model)]
    for cls in sc.classes.values():
        for mm in cls.methods.values():
            all_models.append((cls.name, mm))
            kinds = {b.kind for b in mm.blocking}
            if kinds:
                fn_blocks[(cls.name, mm.name)] = kinds
                if cls.name == "<module-fns>":
                    fn_blocks[("", mm.name)] = kinds

    def report(b: _BlockingCall):
        if b.kind == "socket":
            # rule 4's per-endpoint allowlist: every held lock is a
            # per-endpoint connection lock
            if all(_is_per_endpoint(h) for h in b.held):
                return
        sup = sc.suppressed("blocking-under-lock", b.line,
                            *b.with_lines)
        held = ", ".join(sorted(b.held))
        findings.append(Finding(
            "blocking-under-lock",
            "info" if sup else "error", sc.path, b.line,
            f"blocking {b.kind} call "
            f"{(b.receiver + '.') if b.receiver else ''}"
            f"{b.name}() while holding {held} — every thread "
            "needing the lock convoys behind it",
            hint=hints[b.kind], suppressed=sup))

    for cname, mm in all_models:
        for b in mm.blocking:
            if b.held:
                report(b)
        # one hop transitive: calling a same-file helper that itself
        # makes a direct blocking call, with a lock held (warning: the
        # helper may have its own discipline the analysis cannot see)
        for c in mm.calls:
            if not c.held:
                continue
            kinds = fn_blocks.get((cname, c.method)) \
                or fn_blocks.get(("", c.method)) \
                or fn_blocks.get(("<module-fns>", c.method))
            if not kinds:
                continue
            sup = sc.suppressed("blocking-under-lock", c.line,
                                *c.with_lines)
            findings.append(Finding(
                "blocking-under-lock",
                "info" if sup else "warning", sc.path, c.line,
                f"call to {c.method}(), which makes a blocking "
                f"{'/'.join(sorted(kinds))} call, while holding "
                + ", ".join(sorted(c.held)),
                hint="the helper blocks with the lock held — move "
                "the call outside the lock or annotate why the "
                "convoy is acceptable",
                suppressed=sup))


def _check_races(sc: _FileScanner, findings: List[Finding]):
    for cls in sc.classes.values():
        if not cls.thread_targets or cls.name == "<module-fns>":
            continue   # single-threaded class: nothing to race
        # background set: thread targets + methods reachable from them
        bg = set(cls.thread_targets)
        changed = True
        while changed:
            changed = False
            for m in list(bg):
                mm = cls.methods.get(m)
                if mm is None:
                    continue
                for c in mm.calls:
                    if c.method in cls.methods and c.method not in bg:
                        bg.add(c.method)
                        changed = True

        # methods reachable ONLY from __init__ run pre-publication (no
        # other thread can hold the object yet) — same exemption as
        # __init__ itself
        callers: Dict[str, Set[str]] = {}
        for mname, mm in cls.methods.items():
            for c in mm.calls:
                callers.setdefault(c.method, set()).add(mname)
        init_only: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for m in cls.methods:
                if m in init_only or m == "__init__" or m in bg:
                    continue
                cs = callers.get(m)
                if cs and cs <= ({"__init__"} | init_only):
                    init_only.add(m)
                    changed = True
        pre_pub = {"__init__"} | init_only

        # guarded attrs: written under some lock outside __init__
        guards: Dict[str, Set[str]] = {}
        writers: Dict[str, Set[str]] = {}
        all_writes_flag: Dict[str, bool] = {}
        for mname, mm in cls.methods.items():
            for a in mm.accesses:
                if not a.is_write:
                    continue
                all_writes_flag[a.attr] = (
                    all_writes_flag.get(a.attr, True) and a.flag_write)
                if mname not in pre_pub and a.held:
                    guards.setdefault(a.attr, set()).update(a.held)
                    writers.setdefault(a.attr, set()).add(mname)
        skip = set(cls.locks) | {t.split(".", 1)[-1]
                                 for t in cls.threads} \
            | set(cls.methods)
        for mname, mm in cls.methods.items():
            if mname in pre_pub:
                continue
            if mname.endswith("_locked"):
                # naming convention: the caller holds the lock — the
                # bare accesses inside are the point of the helper
                continue
            for a in mm.accesses:
                if a.attr not in guards or a.attr in skip or a.held:
                    continue
                # bare access in a method on the other side of a thread
                # boundary from some locked writer
                other_side = any(
                    (w in bg) != (mname in bg)
                    for w in writers.get(a.attr, ()))
                if not other_side:
                    continue
                sup = sc.suppressed("unguarded-attr", a.line)
                # pure bool/None flag attrs (`self._stop = True`):
                # the CPython store/load is atomic and the pattern is
                # idiomatic cooperative shutdown — info, not warning
                flagish = all_writes_flag.get(a.attr, False) or (
                    a.is_write and a.flag_write)
                findings.append(Finding(
                    "unguarded-attr",
                    "info" if (sup or flagish) else "warning",
                    sc.path, a.line,
                    f"{cls.name}.{a.attr} is written under "
                    f"{'/'.join(sorted(guards[a.attr]))} in "
                    f"{'/'.join(sorted(writers[a.attr]))} but "
                    f"{'written' if a.is_write else 'read'} with no "
                    f"lock in {mname}(), which runs on a different "
                    "thread — data race candidate",
                    hint="take the attribute's lock here too, or "
                    "annotate `# lint: unguarded-attr-ok` with why "
                    "the bare access is safe (atomic flag, "
                    "happens-before via join, ...)",
                    suppressed=sup))


def _check_thread_hygiene(sc: _FileScanner, findings: List[Finding]):
    decls: List[Tuple[Optional[_ClassModel], _ThreadDecl]] = []
    for cls in sc.classes.values():
        for decl in cls.threads.values():
            decls.append((cls, decl))
    for decl in sc.local_threads:
        decls.append((None, decl))

    for cls, decl in decls:
        if decl.daemon is True:
            continue
        joined = decl.joined or decl.name in sc.joined_names
        if not joined:
            sup = sc.suppressed("thread-join", decl.line)
            findings.append(Finding(
                "thread-join", "info" if sup else "error",
                sc.path, decl.line,
                f"non-daemon thread {decl.name} is never joined — it "
                "keeps the process alive after main exits (and its "
                "failures are never observed)",
                hint="pass daemon=True (fire-and-forget workers) or "
                "join it on the shutdown path",
                suppressed=sup))

    # start-before-state: a thread started in a method whose target
    # reads attrs first assigned AFTER the start() in that same method
    for cls in sc.classes.values():
        for decl in cls.threads.values():
            if decl.started_line is None or not decl.target:
                continue
            target = cls.methods.get(decl.target)
            if target is None:
                continue
            reads = {a.attr for a in target.accesses if not a.is_write}
            # plus attrs read by the target's callees (one hop deep is
            # where the real bugs live; full closure adds noise)
            for c in target.calls:
                callee = cls.methods.get(c.method)
                if callee:
                    reads |= {a.attr for a in callee.accesses
                              if not a.is_write}
            for mname, mm in cls.methods.items():
                assigns: Dict[str, int] = {}
                for a in mm.accesses:
                    if a.is_write and a.attr not in assigns:
                        assigns[a.attr] = a.line
                start_here = any(
                    ln == decl.started_line
                    for (ln, ev, recv) in mm.stmt_events
                    if ev == "start" and recv == decl.name)
                if not start_here:
                    continue
                late = sorted(
                    (ln, attr) for attr, ln in assigns.items()
                    if attr in reads and ln > decl.started_line)
                if late:
                    ln, attr = late[0]
                    sup = sc.suppressed("thread-start-order",
                                        decl.started_line, ln)
                    findings.append(Finding(
                        "thread-start-order",
                        "info" if sup else "error",
                        sc.path, decl.started_line,
                        f"{decl.name}.start() runs "
                        f"{decl.target}() which reads self.{attr}, "
                        f"first assigned at line {ln} — after the "
                        "start: the thread can observe it missing",
                        hint="assign every attribute the thread reads "
                        "before start(), or gate the thread body on "
                        "an Event set when initialization completes",
                        suppressed=sup))


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def analyze_source(source: str, filename: str = "<source>",
                   rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Analyze one source string; `rules` restricts the checks run."""
    tree = ast.parse(source, filename=filename)
    sc = _FileScanner(tree, filename, source)
    findings: List[Finding] = []
    rules = set(rules or RULES)
    if "lock-order" in rules:
        _check_lock_order(sc, findings)
    if "blocking-under-lock" in rules:
        _check_blocking(sc, findings)
    if "unguarded-attr" in rules:
        _check_races(sc, findings)
    if "thread-join" in rules or "thread-start-order" in rules:
        hygiene: List[Finding] = []
        _check_thread_hygiene(sc, hygiene)
        findings.extend(f for f in hygiene if f.rule in rules)
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def analyze_file(path: str,
                 rules: Optional[Sequence[str]] = None) -> List[Finding]:
    with open(path) as f:
        source = f.read()
    try:
        return analyze_source(source, filename=path, rules=rules)
    except SyntaxError as e:
        # a file the analyzer cannot parse is ALWAYS an error (never
        # filtered by `rules` — an unanalyzable file must not read as
        # clean), under its own rule id so consumers don't misfile it
        # as a deadlock finding
        return [Finding("syntax-error", "error", path, e.lineno or 0,
                        f"syntax error: {e.msg}")]


def iter_py_files(paths: Sequence[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def analyze_paths(paths: Optional[Sequence[str]] = None,
                  rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Analyze files/dirs (default: the whole paddle_tpu package)."""
    out: List[Finding] = []
    for path in iter_py_files(list(paths or DEFAULT_PATHS)):
        out.extend(analyze_file(path, rules=rules))
    return out


def to_diagnostics(findings: Sequence[Finding]):
    """Render findings on the shared PR 3 Diagnostic model (file/line in
    the source-location fields) — the `cli concurrency --json` shape."""
    from .diagnostics import Diagnostic

    out = []
    for f in findings:
        rel = os.path.relpath(f.file, _REPO_ROOT) \
            if os.path.isabs(f.file) else f.file
        out.append(Diagnostic(
            pass_id=f"concurrency/{f.rule}", severity=f.severity,
            message=f.message + (" [suppressed]" if f.suppressed else ""),
            hint=f.hint, file=rel, line=f.line))
    return out


def summarize(findings: Sequence[Finding]) -> str:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    n_sup = sum(1 for f in findings if f.suppressed)
    head = ", ".join(f"{counts[s]} {s}{'s' if counts[s] != 1 else ''}"
                     for s in ("error", "warning", "info")
                     if s in counts) or "no findings"
    if n_sup:
        head += f" ({n_sup} suppressed)"
    return head


if __name__ == "__main__":   # ad-hoc: python -m paddle_tpu.analysis.concurrency
    import sys

    fs = analyze_paths(sys.argv[1:] or None)
    for f in fs:
        print(f)
    print(summarize(fs))
    sys.exit(1 if any(f.severity == "error" for f in fs) else 0)
