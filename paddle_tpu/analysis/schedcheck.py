"""Deterministic-schedule checker: explore thread interleavings.

The AST analyzer (concurrency.py) catches lock-order and lock-discipline
bugs statically; this module catches the PROTOCOL bugs — lost wakeups,
stop/accept races, drain/swap ordering — by actually running small
threaded models under a cooperative scheduler that serializes execution
and explores interleavings (CHESS-style):

* inside a `run()` / `explore()` call, `threading.Lock/RLock/Condition/
  Event/Semaphore/Thread` are patched to cooperative shims (code under
  test needs NO changes; `queue.Queue` built during the run composes,
  since it builds on `threading` primitives at construction time);
* exactly ONE thread runs at a time; every primitive operation is a
  yield point where the scheduler picks the next runnable thread —
  bounded DFS over the choice tree first (systematic), then seeded
  random schedules (diversity past the bound);
* a schedule with live threads and nothing runnable is a DEADLOCK,
  reported with each thread's blocked-on state and the full decision
  trace (replayable: pass the trace back as `prefix`);
* timed waits (`wait(timeout=...)`, `join(timeout)`) never block a
  schedule forever: when nothing else is runnable the scheduler wakes
  one timed waiter with a timeout result — exploring the timeout path
  without real time.

Invariant hooks: the model callable returns a state object; each
schedule's state is passed to `invariant(state)` which raises (any
AssertionError/Exception) to flag the schedule.  `explore()` collects
the first violation with its schedule trace; `check()` raises it.

Protocol models for the distributed runtime (FENCE->MIGRATE->COMMIT,
elastic_round replay, GenerationServer admit/finish/swap over the REAL
PagedKVCache, CommPool.send_round ordering) live in schedmodels.py;
regression pins for previously hand-fixed races re-run the REAL
pserver/serving code under this scheduler with the old bug reintroduced
via `arm_fault` (docs/analysis.md "Schedule checking").
"""
from __future__ import annotations

import contextlib
import dataclasses
import random
import threading as _threading
from typing import Callable, List, Optional, Sequence, Tuple

__all__ = [
    "DeadlockError",
    "ScheduleViolation",
    "ExplorationResult",
    "explore",
    "check",
    "run_schedule",
    "yield_point",
    "arm_fault",
    "fault_armed",
]

# the REAL primitives (captured before any patching)
_RealThread = _threading.Thread
_RealLock = _threading.Lock
_RealRLock = _threading.RLock
_RealCondition = _threading.Condition
_RealEvent = _threading.Event
_RealSemaphore = _threading.Semaphore
_RealBoundedSemaphore = _threading.BoundedSemaphore
_real_current = _threading.current_thread

_MAX_STEPS = 20_000   # runaway-schedule backstop (livelock guard)


class DeadlockError(AssertionError):
    """All live threads blocked with no timed waiter to wake."""


class ScheduleViolation(AssertionError):
    """One schedule violated an invariant (or deadlocked).

    `trace` replays it: `run_schedule(model, prefix=violation.trace)`.
    """

    def __init__(self, message: str, trace: List[int],
                 schedule_index: int):
        super().__init__(message)
        self.trace = list(trace)
        self.schedule_index = schedule_index


# ---------------------------------------------------------------------------
# fault toggles: reintroduce previously-fixed bugs for regression pins
# ---------------------------------------------------------------------------

_ARMED_FAULTS: set = set()


def fault_armed(name: str) -> bool:
    """Production modules guard their regression-pin code paths on this
    (e.g. parallel/pserver.py's accept-vs-stop check).  Always False
    outside a test that armed the fault."""
    return name in _ARMED_FAULTS


@contextlib.contextmanager
def arm_fault(name: str):
    """Reintroduce one historical bug while the context is active — the
    schedule checker must then find its race deterministically."""
    _ARMED_FAULTS.add(name)
    try:
        yield
    finally:
        _ARMED_FAULTS.discard(name)


# ---------------------------------------------------------------------------
# the scheduler
# ---------------------------------------------------------------------------


class _Abort(BaseException):
    """Raised inside coop threads to unwind them on teardown (BaseException
    so model code's `except Exception` cannot swallow the teardown)."""


class _Coop:
    """One cooperative thread: a real thread gated on a semaphore the
    scheduler controls; at most one gate is open at any time."""

    __slots__ = ("name", "gate", "state", "blocked_on", "timed",
                 "real", "exc", "result", "target", "daemon", "joiners")

    def __init__(self, name: str, target: Callable):
        self.name = name
        self.joiners: List["_Coop"] = []
        # the gate must be a fully REAL semaphore even when created
        # while the patch is installed (its internals resolve
        # threading.Condition at construction time)
        with _pause_patch():
            self.gate = _RealSemaphore(0)
        self.state = "new"        # new|runnable|blocked|finished
        self.blocked_on = ""      # human description when blocked
        self.timed = False        # blocked in a TIMED wait/join
        self.real: Optional[_threading.Thread] = None
        self.exc: Optional[BaseException] = None
        self.target = target
        self.daemon = True


class Scheduler:
    def __init__(self, prefix: Sequence[int], rng: random.Random):
        self.threads: List[_Coop] = []
        self.current: Optional[_Coop] = None
        self.sched_gate = _RealSemaphore(0)
        self.prefix = list(prefix)
        self.rng = rng
        self.trace: List[int] = []
        self.choice_counts: List[int] = []
        self.steps = 0
        self.aborting = False
        self.deadlock: Optional[str] = None
        # maps real thread ident -> coop (for current-thread dispatch)
        self._by_ident = {}

    # -- thread lifecycle ----------------------------------------------------
    def spawn(self, coop: _Coop):
        def body():
            self._by_ident[_real_current().ident] = coop
            coop.gate.acquire()      # wait to be scheduled first
            try:
                if not self.aborting:
                    coop.target()
            except _Abort:
                pass
            except BaseException as e:
                coop.exc = e
            finally:
                coop.state = "finished"
                for j in coop.joiners:
                    self.unblock(j)
                coop.joiners.clear()
                self.sched_gate.release()

        # real Thread construction/start resolves threading.Event &co.
        # at call time — pause the patch so its internals stay real
        with _pause_patch():
            coop.real = _RealThread(target=body, daemon=True,
                                    name=f"sched-{coop.name}")
            self.threads.append(coop)
            coop.state = "runnable"
            coop.real.start()

    def current_coop(self) -> Optional[_Coop]:
        return self._by_ident.get(_real_current().ident)

    # -- core switch ---------------------------------------------------------
    def yield_point(self, reason: str = "yield"):
        """Called from inside a coop thread: hand control back to the
        scheduler and wait to be rescheduled."""
        me = self.current_coop()
        if me is None:
            return   # unmanaged thread (e.g. real metrics internals)
        if self.aborting:
            raise _Abort()
        me.blocked_on = reason
        self.sched_gate.release()
        me.gate.acquire()
        if self.aborting:
            raise _Abort()

    def block(self, reason: str, timed: bool = False):
        me = self.current_coop()
        if me is None or self.aborting:
            if me is not None and self.aborting:
                raise _Abort()
            return
        me.state = "blocked"
        me.blocked_on = reason
        me.timed = timed
        self.sched_gate.release()
        me.gate.acquire()
        if self.aborting:
            raise _Abort()

    def unblock(self, coop: _Coop):
        if coop.state == "blocked":
            coop.state = "runnable"
            coop.timed = False

    # -- main loop -----------------------------------------------------------
    def loop(self):
        """Run until every coop thread finishes (or deadlock/abort)."""
        while True:
            live = [t for t in self.threads if t.state != "finished"]
            if not live:
                return
            if all(t.daemon for t in live):
                # only daemon threads left (the model body finished):
                # process-exit semantics — a parked accept loop or
                # worker is not a deadlock
                self.abort()
                return
            runnable = [t for t in live if t.state == "runnable"]
            if not runnable:
                timed = [t for t in live if t.timed]
                if not timed:
                    self.deadlock = "; ".join(
                        f"{t.name}: blocked on {t.blocked_on}"
                        for t in live)
                    self.abort()
                    return
                # wake one timed waiter with a timeout result: real
                # time never passes, the timeout path is just another
                # scheduling choice
                runnable = timed
            self.steps += 1
            if self.steps > _MAX_STEPS:
                self.deadlock = (
                    f"schedule exceeded {_MAX_STEPS} steps — livelock "
                    "(threads spinning on timed waits?)")
                self.abort()
                return
            idx = self._choose(len(runnable))
            t = runnable[idx]
            if t.state == "blocked":    # a timed waiter woken by choice
                t.state = "runnable"
                t.timed = False
                t.blocked_on = "timeout-wakeup"
            self.current = t
            t.gate.release()
            self.sched_gate.acquire()

    def _choose(self, n: int) -> int:
        self.choice_counts.append(n)
        if n == 1:
            self.trace.append(0)
            return 0
        d = len(self.trace)
        if d < len(self.prefix):
            idx = min(self.prefix[d], n - 1)
        elif self.rng is not None:
            idx = self.rng.randrange(n)
        else:
            idx = 0
        self.trace.append(idx)
        return idx

    def abort(self):
        """Unwind every live coop thread (they raise _Abort at their
        next gate release) and join them."""
        self.aborting = True
        for t in self.threads:
            if t.state != "finished":
                t.gate.release()
        for t in self.threads:
            if t.real is not None:
                t.real.join(timeout=5)


_SCHED: Optional[Scheduler] = None


def _sched() -> Optional[Scheduler]:
    return _SCHED


def yield_point(reason: str = "model"):
    """Public yield point for models/fakes (e.g. a fake socket's accept)
    so the scheduler can interleave around non-threading operations."""
    s = _SCHED
    if s is not None:
        s.yield_point(reason)


# ---------------------------------------------------------------------------
# cooperative primitive shims (installed by _patched during a run)
# ---------------------------------------------------------------------------


class CoopLock:
    _reentrant = False

    def __init__(self):
        self._owner: Optional[_Coop] = None
        self._count = 0
        self._waiters: List[_Coop] = []
        self._real = _RealLock()   # fallback for unmanaged threads

    def acquire(self, blocking: bool = True, timeout: float = -1):
        s = _SCHED
        me = s.current_coop() if s is not None else None
        if me is None:
            # pass timeout through verbatim: 0 is a valid poll, and
            # the default -1 already means "no timeout"
            return self._real.acquire(blocking, timeout)
        s.yield_point(f"acquire {id(self):#x}")
        while self._owner is not None and self._owner is not me:
            if not blocking:
                return False
            self._waiters.append(me)
            s.block(f"lock {id(self):#x} held by {self._owner.name}",
                    timed=timeout is not None and timeout >= 0)
            if me in self._waiters:
                self._waiters.remove(me)
            if (timeout is not None and timeout >= 0
                    and self._owner is not None
                    and self._owner is not me):
                return False   # woken by timeout choice
        if self._owner is me:
            if not self._reentrant:
                raise RuntimeError(
                    "cooperative Lock re-acquired by its owner "
                    "(self-deadlock in real threading)")
            self._count += 1
            return True
        self._owner = me
        self._count = 1
        return True

    def release(self):
        s = _SCHED
        me = s.current_coop() if s is not None else None
        if me is None:
            return self._real.release()
        if self._owner is not me:
            if s.aborting:
                return   # unwinding a with-block torn mid-acquire
            raise RuntimeError("release of un-owned cooperative lock")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            for w in list(self._waiters):
                s.unblock(w)

    def locked(self):
        return self._owner is not None or self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class CoopRLock(CoopLock):
    _reentrant = True


class CoopCondition:
    def __init__(self, lock=None):
        self._lock = lock if lock is not None else CoopRLock()
        self._waiting: List[Tuple[_Coop, list]] = []

    # delegate lock protocol
    def acquire(self, *a, **k):
        return self._lock.acquire(*a, **k)

    def release(self):
        return self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def _is_owned(self):
        s = _SCHED
        me = s.current_coop() if s is not None else None
        return getattr(self._lock, "_owner", None) is me \
            and me is not None

    def wait(self, timeout: Optional[float] = None):
        s = _SCHED
        me = s.current_coop() if s is not None else None
        if me is None:
            raise RuntimeError(
                "cooperative Condition.wait from unmanaged thread")
        if getattr(self._lock, "_owner", None) is not me:
            raise RuntimeError("wait() on un-acquired Condition")
        token = [False]   # [notified]
        self._waiting.append((me, token))
        # release fully (even through RLock reentrancy)
        count = getattr(self._lock, "_count", 1)
        for _ in range(count):
            self._lock.release()
        s.block(f"cond-wait {id(self):#x}", timed=timeout is not None)
        if (me, token) in self._waiting:
            self._waiting.remove((me, token))
        for _ in range(count):
            self._lock.acquire()
        return token[0]

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # bounded: a timed wait_for can return a False predicate (the
        # timeout path); an untimed one loops until the predicate holds
        while not predicate():
            if not self.wait(timeout) and timeout is not None:
                return predicate()
        return True

    def notify(self, n: int = 1):
        s = _SCHED
        woken = 0
        for (w, token) in list(self._waiting):
            if woken >= n:
                break
            token[0] = True
            self._waiting.remove((w, token))
            if s is not None:
                s.unblock(w)
            woken += 1

    def notify_all(self):
        self.notify(len(self._waiting))


class CoopEvent:
    def __init__(self):
        self._flag = False
        self._waiters: List[_Coop] = []
        # real mirror: unmanaged threads wait on the real event instead
        with _pause_patch():
            self._real = _RealEvent()

    def is_set(self):
        return self._flag

    def set(self):
        s = _SCHED
        self._flag = True
        self._real.set()
        for w in list(self._waiters):
            if s is not None:
                s.unblock(w)
        self._waiters.clear()

    def clear(self):
        self._flag = False
        self._real.clear()

    def wait(self, timeout: Optional[float] = None):
        s = _SCHED
        me = s.current_coop() if s is not None else None
        if me is None:
            return self._real.wait(timeout)
        s.yield_point("event-check")
        while not self._flag:
            self._waiters.append(me)
            s.block(f"event {id(self):#x}", timed=timeout is not None)
            if me in self._waiters:
                self._waiters.remove(me)
            if timeout is not None and not self._flag:
                return False   # timeout path chosen
        return True


class CoopSemaphore:
    def __init__(self, value: int = 1):
        self._value = int(value)
        self._waiters: List[_Coop] = []

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None):
        s = _SCHED
        me = s.current_coop() if s is not None else None
        if me is None:
            raise RuntimeError(
                "cooperative Semaphore from unmanaged thread")
        s.yield_point("sem-acquire")
        while self._value <= 0:
            if not blocking:
                return False
            self._waiters.append(me)
            s.block(f"semaphore {id(self):#x}",
                    timed=timeout is not None)
            if me in self._waiters:
                self._waiters.remove(me)
            if timeout is not None and self._value <= 0:
                return False
        self._value -= 1
        return True

    def release(self, n: int = 1):
        s = _SCHED
        self._value += n
        for w in list(self._waiters):
            if s is not None:
                s.unblock(w)
        self._waiters.clear()

    __enter__ = lambda self: self.acquire() and self  # noqa: E731

    def __exit__(self, *exc):
        self.release()
        return False


class CoopThread:
    """threading.Thread stand-in: registers with the active scheduler on
    start(); runs as a gated real thread."""

    _counter = [0]

    def __init__(self, group=None, target=None, name=None, args=(),
                 kwargs=None, *, daemon=None):
        self._target = target
        self._args = tuple(args)
        self._kwargs = dict(kwargs or {})
        CoopThread._counter[0] += 1
        self.name = name or f"CoopThread-{CoopThread._counter[0]}"
        self.daemon = bool(daemon) if daemon is not None else False
        self._coop: Optional[_Coop] = None

    def run(self):
        if self._target is not None:
            self._target(*self._args, **self._kwargs)

    def start(self):
        s = _SCHED
        if s is None:
            raise RuntimeError(
                "CoopThread.start outside a schedcheck run")
        if self._coop is not None:
            raise RuntimeError("threads can only be started once")
        self._coop = _Coop(self.name, self.run)
        self._coop.daemon = self.daemon
        s.spawn(self._coop)
        s.yield_point("thread-start")

    def is_alive(self):
        return self._coop is not None \
            and self._coop.state != "finished"

    def join(self, timeout: Optional[float] = None):
        s = _SCHED
        me = s.current_coop() if s is not None else None
        if self._coop is None:
            return
        if me is None:
            self._coop.real.join(timeout)
            return
        while self._coop.state != "finished":
            self._coop.joiners.append(me)
            s.block(f"join {self.name}", timed=timeout is not None)
            if me in self._coop.joiners:
                self._coop.joiners.remove(me)
            if timeout is not None \
                    and self._coop.state != "finished":
                return   # timeout path chosen
        s.yield_point("joined")


_COOP_CLASSES = {
    "Thread": CoopThread,
    "Lock": CoopLock,
    "RLock": CoopRLock,
    "Condition": CoopCondition,
    "Event": CoopEvent,
    "Semaphore": CoopSemaphore,
    "BoundedSemaphore": CoopSemaphore,
}
_SAVED: Optional[dict] = None


def _apply_coop():
    for n, v in _COOP_CLASSES.items():
        setattr(_threading, n, v)


@contextlib.contextmanager
def _pause_patch():
    """Temporarily restore the REAL threading primitives (a scheduler
    internal constructing real threads/events mid-run).  No-op when the
    patch is not installed.  Safe because exactly one coop thread (or
    the scheduler) runs at any instant."""
    if _SAVED is None:
        yield
        return
    for n, v in _SAVED.items():
        setattr(_threading, n, v)
    try:
        yield
    finally:
        _apply_coop()


@contextlib.contextmanager
def _patched():
    global _SAVED
    _SAVED = {n: getattr(_threading, n) for n in _COOP_CLASSES}
    saved = _SAVED
    _apply_coop()
    try:
        yield
    finally:
        for n, v in saved.items():
            setattr(_threading, n, v)
        _SAVED = None


# ---------------------------------------------------------------------------
# exploration drivers
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ScheduleResult:
    trace: List[int]
    choice_counts: List[int]
    state: object = None
    error: Optional[BaseException] = None
    deadlock: Optional[str] = None


def run_schedule(model: Callable[[], object],
                 prefix: Sequence[int] = (),
                 seed: Optional[int] = None) -> ScheduleResult:
    """Run `model` once under the cooperative scheduler.  Decisions
    follow `prefix`, then a seeded RNG (or first-runnable when seed is
    None).  The model body itself runs as the first coop thread."""
    global _SCHED
    if _SCHED is not None:
        raise RuntimeError("schedcheck runs cannot nest")
    rng = random.Random(seed) if seed is not None else None
    sched = Scheduler(prefix, rng)
    res = ScheduleResult([], [])

    def main_body():
        res.state = model()

    main = _Coop("main", main_body)
    main.daemon = False   # the model body is the process's main thread
    _SCHED = sched
    try:
        with _patched():
            sched.spawn(main)
            sched.loop()
    finally:
        _SCHED = None
    res.trace = sched.trace
    res.choice_counts = sched.choice_counts
    if sched.deadlock is not None:
        res.deadlock = sched.deadlock
    for t in sched.threads:
        if t.exc is not None and res.error is None:
            res.error = t.exc
    return res


@dataclasses.dataclass
class ExplorationResult:
    schedules: int
    violation: Optional[ScheduleViolation]

    @property
    def ok(self) -> bool:
        return self.violation is None


def explore(model: Callable[[], object],
            invariant: Optional[Callable[[object], None]] = None,
            *, max_schedules: int = 200, seed: int = 0,
            random_schedules: int = 50) -> ExplorationResult:
    """Bounded DFS over the schedule tree, then seeded random schedules.

    DFS: replay a recorded decision prefix, take the FIRST branch past
    it, and push every untaken alternative of the completed schedule
    onto the stack (deepest first) — systematic coverage of the
    low-order interleavings where protocol races live.  Random: seeds
    `seed`..`seed+random_schedules-1` shake out deeper orderings.
    Returns the first violation (invariant failure, model exception, or
    deadlock) with its replayable trace."""
    schedules = 0

    def attempt(prefix, seed_):
        nonlocal schedules
        res = run_schedule(model, prefix, seed_)
        schedules += 1
        problem: Optional[str] = None
        if res.deadlock is not None:
            problem = f"deadlock: {res.deadlock}"
        elif res.error is not None:
            problem = (f"{type(res.error).__name__}: {res.error}")
        elif invariant is not None:
            try:
                invariant(res.state)
            except BaseException as e:
                problem = f"invariant violated: {e}"
        if problem is not None:
            return res, ScheduleViolation(
                f"schedule {schedules - 1} "
                f"(trace {res.trace}): {problem}",
                res.trace, schedules - 1)
        return res, None

    # DFS phase (deterministic: first-runnable past the prefix); each
    # completed schedule contributes every untaken branch along its
    # trace, deepest pushed last so the stack pops depth-first
    stack: List[List[int]] = [[]]
    explored = {()}
    while stack and schedules < max_schedules:
        prefix = stack.pop()
        res, v = attempt(prefix, None)
        if v is not None:
            return ExplorationResult(schedules, v)
        for d in range(len(prefix), len(res.trace)):
            n = res.choice_counts[d]
            for alt in range(n):
                if alt == res.trace[d]:
                    continue
                cand = res.trace[:d] + [alt]
                key = tuple(cand)
                if key not in explored:
                    explored.add(key)
                    stack.append(cand)

    # random phase: seeded diversity past the DFS bound
    for i in range(random_schedules):
        res, v = attempt((), seed + i)
        if v is not None:
            return ExplorationResult(schedules, v)
    return ExplorationResult(schedules, None)


def check(model: Callable[[], object],
          invariant: Optional[Callable[[object], None]] = None,
          **kw) -> int:
    """explore() that RAISES the violation; returns schedules explored."""
    res = explore(model, invariant, **kw)
    if res.violation is not None:
        raise res.violation
    return res.schedules
