"""Mixed-precision (bfloat16) training support.

Reference: /root/reference/doc/design/float16.md (the fp16 design note —
the reference never shipped a training AMP; math/float16.h is an
interchange type).  The TPU rebuild makes bf16 a first-class training
mode, designed around the MXU and HBM:

  * Whitelisted MXU ops (mul / matmul / conv2d family) cast float32
    operands to bfloat16 at their input edge — XLA fuses the converts into
    the surrounding computation, so activations flow through the network
    in bf16 (half the HBM traffic) and matmuls/convs hit the MXU's native
    bf16 path.
  * Parameters stay float32 ("master weights").  The generic-VJP backward
    produces bf16 grads for bf16 compute; optimizer ops then apply them to
    f32 params, where jnp type promotion upcasts — no grad-scaling loop is
    needed because bf16 has f32's exponent range (unlike fp16).
  * Numerically sensitive tails (softmax, cross-entropy) upcast their
    inputs back to f32 inside their own lowerings.

Usage:
    with fluid.amp.bf16_guard():
        exe.run(main, feed=..., fetch_list=[loss])
or process-wide: fluid.amp.enable_bf16() / PADDLE_TPU_AMP_BF16=1.

NOTE: the flag is read at TRACE time inside op lowerings, and toggling it
does not change input avals — so every compile cache must key on it
explicitly.  Executor includes the flag in its cache keys and
ParallelExecutor refreshes its jit on a flag flip; code that jits
`program_to_fn` directly (e.g. bench.py) must set the amp state before
tracing and keep it fixed.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from .core.flags import get_flag, set_flags

__all__ = ["enable_bf16", "disable_bf16", "bf16_guard", "amp_cast",
           "amp_upcast", "is_bf16_enabled"]


def is_bf16_enabled() -> bool:
    return bool(get_flag("amp_bf16"))


def enable_bf16():
    set_flags({"amp_bf16": True})


def disable_bf16():
    set_flags({"amp_bf16": False})


@contextlib.contextmanager
def bf16_guard():
    prev = is_bf16_enabled()
    set_flags({"amp_bf16": True})
    try:
        yield
    finally:
        set_flags({"amp_bf16": prev})


def amp_cast(*arrays):
    """Whitelist-edge cast: float32 -> bfloat16 when amp is on (other
    dtypes pass through untouched)."""
    if not is_bf16_enabled():
        return arrays if len(arrays) > 1 else arrays[0]
    out = tuple(
        a.astype(jnp.bfloat16)
        if hasattr(a, "dtype") and a.dtype == jnp.float32 else a
        for a in arrays
    )
    return out if len(out) > 1 else out[0]


def amp_upcast(a):
    """Blacklist-edge cast: bfloat16 -> float32 for numerically sensitive
    ops (softmax/cross-entropy) while amp is on.  Gated on the flag so
    programs that are deliberately pure-bf16 (no amp) keep their dtypes."""
    if is_bf16_enabled() and hasattr(a, "dtype") \
            and a.dtype == jnp.bfloat16:
        return a.astype(jnp.float32)
    return a
