// C inference ABI over the embedded-Python engine (capi_runtime.py).
//
// Reference: /root/reference/paddle/capi/ (gradient_machine.h
// paddle_gradient_machine_create_for_inference_with_parameters, forward;
// examples/model_inference) — a pure-C embedding surface for trained
// models.  The TPU rebuild keeps the C ABI shape but the engine is the
// Python framework (XLA executor) reached through CPython: the host app
// links _capi.so, everything Python stays behind these six functions.
//
// Works both ways: from a standalone C program (initializes an embedded
// interpreter; set PYTHONPATH to the repo/site-packages) and from inside
// an existing Python process via ctypes (uses the live interpreter).
//
// All functions return 0 on success (or a handle); on failure they return
// nonzero/NULL and paddle_tpu_last_error() describes the Python exception.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>

namespace {

// Per-thread so the pointer returned by paddle_tpu_last_error() stays
// valid while other threads fail concurrently.
thread_local std::string g_last_error;
std::once_flag g_init_once;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "unknown error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  g_last_error = msg;
}

void set_error(const char* msg) { g_last_error = msg; }

// RAII GIL acquisition that also boots the interpreter on first use when
// running embedded in a plain C program.
class Gil {
 public:
  Gil() {
    std::call_once(g_init_once, [] {
      if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        // release the GIL taken by Py_Initialize so PyGILState works
        PyEval_SaveThread();
      }
    });
    state_ = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

PyObject* runtime_module() {
  return PyImport_ImportModule("paddle_tpu.capi_runtime");
}

// call paddle_tpu.capi_runtime.<fn>(*args); returns new ref or nullptr
PyObject* call_runtime(const char* fn, PyObject* args) {
  PyObject* mod = runtime_module();
  if (mod == nullptr) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) return nullptr;
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return r;
}

}  // namespace

extern "C" {

const char* paddle_tpu_last_error() { return g_last_error.c_str(); }

// -> session handle (>0), or 0 on failure
int64_t paddle_tpu_inference_create(const char* model_dir) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", model_dir);
  PyObject* r = call_runtime("create", args);
  Py_XDECREF(args);
  if (r == nullptr) {
    set_error_from_python();
    return 0;
  }
  int64_t sid = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return sid;
}

// dtype: 0=float32, 1=int64, 2=int32.  dims: ndim entries.
int paddle_tpu_inference_feed(int64_t sid, const char* name,
                              const void* data, const int64_t* dims,
                              int ndim, int dtype) {
  Gil gil;
  int64_t count = 1;
  for (int i = 0; i < ndim; ++i) count *= dims[i];
  const int64_t elem = (dtype == 0) ? 4 : (dtype == 1 ? 8 : 4);
  PyObject* dim_list = PyList_New(ndim);
  if (dim_list == nullptr) {
    set_error("alloc failure");
    return 1;
  }
  for (int i = 0; i < ndim; ++i) {
    PyList_SET_ITEM(dim_list, i, PyLong_FromLongLong(dims[i]));
  }
  PyObject* args = Py_BuildValue(
      "(Lsy#iN)", static_cast<long long>(sid), name,
      static_cast<const char*>(data),
      static_cast<Py_ssize_t>(count * elem), dtype, dim_list);
  if (args == nullptr) {
    set_error_from_python();
    return 1;
  }
  PyObject* r = call_runtime("feed", args);
  Py_DECREF(args);
  if (r == nullptr) {
    set_error_from_python();
    return 1;
  }
  Py_DECREF(r);
  return 0;
}

// runs the model; -> number of fetch outputs, or -1 on failure
int paddle_tpu_inference_run(int64_t sid) {
  Gil gil;
  PyObject* args = Py_BuildValue("(L)", static_cast<long long>(sid));
  PyObject* r = call_runtime("run", args);
  Py_XDECREF(args);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  int n = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return n;
}

// copy output `idx` (as float32) into buf; writes its shape into
// dims/ndim (dims capacity: 8). -> element count, or -1 on failure
// (including buf_capacity too small).
int64_t paddle_tpu_inference_fetch(int64_t sid, int idx, float* buf,
                                   int64_t buf_capacity, int64_t* dims,
                                   int* ndim) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Li)", static_cast<long long>(sid), idx);
  PyObject* r = call_runtime("fetch", args);
  Py_XDECREF(args);
  if (r == nullptr) {
    set_error_from_python();
    return -1;
  }
  // r = (bytes, [dims...])
  PyObject* payload = PyTuple_GetItem(r, 0);
  PyObject* shape = PyTuple_GetItem(r, 1);
  char* raw = nullptr;
  Py_ssize_t nbytes = 0;
  if (payload == nullptr || shape == nullptr ||
      PyBytes_AsStringAndSize(payload, &raw, &nbytes) != 0) {
    Py_DECREF(r);
    set_error_from_python();
    return -1;
  }
  const int64_t count = nbytes / 4;
  if (count > buf_capacity) {
    Py_DECREF(r);
    set_error("fetch buffer too small");
    return -1;
  }
  Py_ssize_t rank = PyList_Size(shape);
  if (rank > 8) {
    Py_DECREF(r);
    set_error("output rank exceeds dims capacity (8)");
    return -1;
  }
  std::memcpy(buf, raw, nbytes);
  if (ndim != nullptr) *ndim = static_cast<int>(rank);
  if (dims != nullptr) {
    for (Py_ssize_t i = 0; i < rank; ++i) {
      dims[i] = PyLong_AsLongLong(PyList_GetItem(shape, i));
    }
  }
  Py_DECREF(r);
  return count;
}

int paddle_tpu_inference_destroy(int64_t sid) {
  Gil gil;
  PyObject* args = Py_BuildValue("(L)", static_cast<long long>(sid));
  PyObject* r = call_runtime("destroy", args);
  Py_XDECREF(args);
  if (r == nullptr) {
    set_error_from_python();
    return 1;
  }
  Py_DECREF(r);
  return 0;
}

}  // extern "C"
