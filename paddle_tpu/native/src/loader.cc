// Native data-loader pipeline: shuffle -> batch -> prefetch, off the GIL.
//
// Reference: /root/reference/paddle/fluid/framework/reader.h (ReaderBase +
// decorator readers: shuffle/batch/double-buffer created by
// operators/create_reader_op.cc) and the legacy async provider
// gserver/dataproviders/PyDataProvider2.cpp (Python generator feeding a
// native buffered pool).  The TPU-native design keeps Python as the sample
// *producer* (ctypes `push` releases the GIL during the copy) while all
// shuffling, batch assembly (the heavy stacking memcpy) and prefetch
// buffering run on a native worker thread over buddy-allocated staging
// memory — host input pipeline overlaps device compute, the XLA-era
// equivalent of the double_buffer reader.
//
// Pipeline stages (single producer or many, one internal worker):
//   push(sample)        -> shuffle buffer (seeded mt19937 shuffle when full)
//   worker thread       -> pops batch_size samples, stacks each slot into a
//                          contiguous per-slot batch buffer
//   ready queue         -> bounded (prefetch_depth), gives backpressure
//   next()/release()    -> consumer borrows a batch, returns it to the pool
//
// Epoch protocol: finish_epoch() flushes the shuffle buffer and enqueues an
// epoch-end marker; next() returns nullptr exactly once per epoch, after
// which the pipeline is ready for the next epoch's pushes.
#include "common.h"

#include <algorithm>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

// from allocator.cc
void* pt_internal_buddy_create(size_t min_log2, size_t chunk_log2);
void* pt_internal_buddy_alloc(void* h, size_t n);
void pt_internal_buddy_free(void* h, void* p);
void pt_internal_buddy_destroy(void* h);

namespace {

struct Batch {
  std::vector<char*> slots;  // one stacked buffer per slot
  size_t n = 0;              // samples in this batch
};

struct Loader {
  std::vector<size_t> slot_nbytes;
  size_t sample_nbytes = 0;  // sum of slots, layout: slot0|slot1|...
  size_t batch_size;
  size_t shuffle_buf;  // 0 = no shuffling (FIFO)
  size_t prefetch_depth;
  bool drop_last;
  std::mt19937_64 rng;

  void* arena;  // buddy allocator owning all staging memory

  std::mutex mu;
  std::condition_variable work_cv;   // worker waits for samples/flush
  std::condition_variable ready_cv;  // consumer waits for batches
  std::condition_variable space_cv;  // worker waits for ready-queue space
  std::condition_variable push_cv;   // producers wait while pending is full

  std::vector<char*> shuffle_pool;   // samples awaiting shuffle
  std::deque<char*> pending;         // shuffled samples awaiting batching
  std::deque<Batch*> ready;          // assembled batches (+nullptr = epoch end)
  bool flush = false;                // epoch flush requested
  bool stop = false;
  uint64_t epochs_ended = 0;

  std::thread worker;

  Loader(size_t n_slots, const size_t* nbytes, size_t bs, size_t shuf,
         uint64_t seed, size_t depth, bool drop)
      : slot_nbytes(nbytes, nbytes + n_slots),
        batch_size(bs),
        shuffle_buf(shuf),
        prefetch_depth(depth ? depth : 2),
        drop_last(drop),
        rng(seed) {
    for (size_t b : slot_nbytes) sample_nbytes += b;
    arena = pt_internal_buddy_create(6, 26);
    worker = std::thread([this] { WorkerLoop(); });
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    work_cv.notify_all();
    ready_cv.notify_all();
    space_cv.notify_all();
    push_cv.notify_all();
    worker.join();
    for (char* s : shuffle_pool) pt_internal_buddy_free(arena, s);
    for (char* s : pending) pt_internal_buddy_free(arena, s);
    for (Batch* b : ready) FreeBatch(b);
    pt_internal_buddy_destroy(arena);
  }

  void FreeBatch(Batch* b) {
    if (!b) return;
    for (char* s : b->slots) pt_internal_buddy_free(arena, s);
    delete b;
  }

  int Push(const void* const* slot_ptrs) {
    char* s = static_cast<char*>(
        pt_internal_buddy_alloc(arena, sample_nbytes));
    if (!s) return 0;
    size_t off = 0;
    for (size_t i = 0; i < slot_nbytes.size(); ++i) {
      std::memcpy(s + off, slot_ptrs[i], slot_nbytes[i]);
      off += slot_nbytes[i];
    }
    std::unique_lock<std::mutex> lk(mu);
    // backpressure: bound staged samples so a fast producer can't outrun
    // the consumer unboundedly (prefetch_depth bounds assembled batches;
    // this bounds raw samples)
    size_t cap = std::max(shuffle_buf, batch_size) + 2 * batch_size;
    push_cv.wait(lk, [&] { return stop || pending.size() < cap; });
    if (stop) {
      pt_internal_buddy_free(arena, s);
      return 0;
    }
    if (shuffle_buf == 0) {
      pending.push_back(s);
      if (pending.size() >= batch_size) work_cv.notify_one();
    } else {
      shuffle_pool.push_back(s);
      if (shuffle_pool.size() >= shuffle_buf) {
        DrainShufflePoolLocked();
        work_cv.notify_one();
      }
    }
    return 1;
  }

  void DrainShufflePoolLocked() {
    std::shuffle(shuffle_pool.begin(), shuffle_pool.end(), rng);
    for (char* s : shuffle_pool) pending.push_back(s);
    shuffle_pool.clear();
  }

  void FinishEpoch() {
    {
      std::lock_guard<std::mutex> lk(mu);
      DrainShufflePoolLocked();
      flush = true;
    }
    work_cv.notify_one();
  }

  void WorkerLoop() {
    for (;;) {
      std::unique_lock<std::mutex> lk(mu);
      work_cv.wait(lk, [&] {
        return stop || pending.size() >= batch_size || flush;
      });
      if (stop) return;
      if (pending.size() < batch_size && !flush) continue;
      size_t take = std::min(pending.size(), batch_size);
      if (take == 0 || (take < batch_size && !flush)) {
        // flush with nothing left: emit epoch end
        if (flush && pending.empty()) {
          flush = false;
          EmitLocked(lk, nullptr);
        }
        continue;
      }
      if (take < batch_size && drop_last) {
        for (size_t i = 0; i < take; ++i) {
          pt_internal_buddy_free(arena, pending.front());
          pending.pop_front();
        }
        push_cv.notify_all();
        continue;
      }
      std::vector<char*> samples(pending.begin(), pending.begin() + take);
      pending.erase(pending.begin(), pending.begin() + take);
      push_cv.notify_all();
      bool end_after =
          flush && pending.empty();  // this is the epoch's last batch
      if (end_after) flush = false;
      lk.unlock();

      // heavy part outside the lock: stack slot-wise
      Batch* b = new Batch();
      b->n = take;
      b->slots.resize(slot_nbytes.size());
      size_t off = 0;
      for (size_t i = 0; i < slot_nbytes.size(); ++i) {
        b->slots[i] = static_cast<char*>(
            pt_internal_buddy_alloc(arena, slot_nbytes[i] * take));
        for (size_t j = 0; j < take; ++j) {
          std::memcpy(b->slots[i] + j * slot_nbytes[i], samples[j] + off,
                      slot_nbytes[i]);
        }
        off += slot_nbytes[i];
      }
      for (char* s : samples) pt_internal_buddy_free(arena, s);

      lk.lock();
      EmitLocked(lk, b);
      if (end_after) EmitLocked(lk, nullptr);
    }
  }

  // enqueue onto the bounded ready queue (nullptr = epoch end marker)
  void EmitLocked(std::unique_lock<std::mutex>& lk, Batch* b) {
    space_cv.wait(lk, [&] { return stop || ready.size() < prefetch_depth; });
    if (stop) {
      FreeBatch(b);
      return;
    }
    ready.push_back(b);
    if (!b) ++epochs_ended;
    ready_cv.notify_one();
  }

  Batch* Next() {
    std::unique_lock<std::mutex> lk(mu);
    ready_cv.wait(lk, [&] { return stop || !ready.empty(); });
    if (stop && ready.empty()) return nullptr;
    Batch* b = ready.front();
    ready.pop_front();
    space_cv.notify_one();
    return b;
  }
};

}  // namespace

PT_API void* pt_loader_create(size_t n_slots, const size_t* slot_nbytes,
                              size_t batch_size, size_t shuffle_buf,
                              uint64_t seed, size_t prefetch_depth,
                              int drop_last) {
  return new Loader(n_slots, slot_nbytes, batch_size, shuffle_buf, seed,
                    prefetch_depth, drop_last != 0);
}

PT_API int pt_loader_push(void* h, const void* const* slot_ptrs) {
  return static_cast<Loader*>(h)->Push(slot_ptrs);
}

PT_API void pt_loader_finish_epoch(void* h) {
  static_cast<Loader*>(h)->FinishEpoch();
}

// Returns a batch handle, or NULL at epoch end (once per finish_epoch).
PT_API void* pt_loader_next(void* h) {
  return static_cast<Loader*>(h)->Next();
}

PT_API size_t pt_batch_n(void* b) { return static_cast<Batch*>(b)->n; }

PT_API void* pt_batch_slot(void* b, size_t i) {
  return static_cast<Batch*>(b)->slots[i];
}

PT_API void pt_batch_release(void* h, void* b) {
  static_cast<Loader*>(h)->FreeBatch(static_cast<Batch*>(b));
}

PT_API void pt_loader_destroy(void* h) { delete static_cast<Loader*>(h); }
