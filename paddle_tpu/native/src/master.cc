// Fault-tolerant task-dispatch master: the Go cloud master, rebuilt native.
//
// Reference: /root/reference/go/master/service.go — dataset partitioned into
// tasks (:106), todo/pending/done queues (:80-88), GetTask per pass (:368),
// TaskFinished (:411), TaskFailed (:455), timeout re-dispatch
// (checkTimeoutFunc :341), discard after failureMax (processFailedTask
// :313), state snapshot/recover (:207,:166 — etcd there, an atomically
// replaced snapshot file here; multi-host deployments put it on shared
// storage).  Trainers are stateless consumers: any may die or join at any
// time (doc/design/cluster_train/README.md), which is the elasticity story
// the TPU rebuild keeps for the host-side data plane while XLA collectives
// own the device plane.
//
// Served two ways: in-process via the C ABI (single-host multi-thread), and
// over a line-oriented TCP protocol (multi-process / multi-host trainers),
// replacing the Go net/rpc + cgo client stack.
#include "common.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fstream>
#include <map>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Task {
  int64_t id = 0;
  int failures = 0;
  std::vector<std::string> chunks;
};

struct Master {
  std::mutex mu;
  std::deque<Task> todo;
  std::map<int64_t, std::pair<Task, Clock::time_point>> pending;
  std::vector<Task> done;
  int64_t discarded = 0;
  int64_t next_id = 0;
  int64_t pass = 0;
  int failure_max;
  double timeout_s;
  std::string snapshot_path;
  bool has_dataset = false;

  // TCP server
  std::atomic<bool> serving{false};
  int listen_fd = -1;
  std::thread server_thread;
  std::vector<std::thread> conn_threads;
  std::mutex conn_mu;

  Master(int fmax, double tsec, const char* snap)
      : failure_max(fmax), timeout_s(tsec),
        snapshot_path(snap ? snap : "") {
    if (!snapshot_path.empty()) Recover();
  }

  ~Master() { StopServe(); }

  // ---- snapshot / recover (reference service.go:207 snapshot, :166) ------
  void SnapshotLocked() {
    if (snapshot_path.empty()) return;
    std::string tmp = snapshot_path + ".tmp";
    {
      std::ofstream f(tmp, std::ios::trunc);
      f << "ptmaster1 " << pass << " " << next_id << " " << discarded
        << "\n";
      auto dump = [&f](const Task& t) {
        f << t.id << " " << t.failures << " " << t.chunks.size() << "\n";
        for (auto& c : t.chunks) f << c << "\n";
      };
      // pending tasks are persisted as todo: after a master restart their
      // trainers may be gone, so they must be re-dispatched (the reference
      // reaches the same end state via recover + timeout).
      f << (todo.size() + pending.size()) << "\n";
      for (auto& t : todo) dump(t);
      for (auto& kv : pending) dump(kv.second.first);
      f << done.size() << "\n";
      for (auto& t : done) dump(t);
    }
    std::rename(tmp.c_str(), snapshot_path.c_str());
  }

  void Recover() {
    std::ifstream f(snapshot_path);
    if (!f) return;
    std::string magic;
    f >> magic;
    if (magic != "ptmaster1") return;
    f >> pass >> next_id >> discarded;
    auto load = [&f](Task& t) {
      size_t n;
      f >> t.id >> t.failures >> n;
      f.ignore();  // trailing newline
      t.chunks.resize(n);
      for (auto& c : t.chunks) std::getline(f, c);
    };
    size_t ntodo, ndone;
    f >> ntodo;
    f.ignore();
    todo.resize(ntodo);
    for (auto& t : todo) load(t);
    f >> ndone;
    f.ignore();
    done.resize(ndone);
    for (auto& t : done) load(t);
    has_dataset = ntodo + ndone > 0;
  }

  // ---- dataset partition (reference service.go:106 partition) ------------
  int SetDataset(const std::vector<std::string>& chunks,
                 size_t chunks_per_task) {
    std::lock_guard<std::mutex> lk(mu);
    if (has_dataset) return 0;  // idempotent, like the reference's once-only
    if (chunks_per_task == 0) chunks_per_task = 1;
    for (size_t i = 0; i < chunks.size(); i += chunks_per_task) {
      Task t;
      t.id = next_id++;
      for (size_t j = i; j < chunks.size() && j < i + chunks_per_task; ++j) {
        t.chunks.push_back(chunks[j]);
      }
      todo.push_back(std::move(t));
    }
    has_dataset = true;
    SnapshotLocked();
    return 1;
  }

  void CheckTimeoutsLocked() {
    auto now = Clock::now();
    for (auto it = pending.begin(); it != pending.end();) {
      double waited =
          std::chrono::duration<double>(now - it->second.second).count();
      if (waited > timeout_s) {
        RequeueLocked(it->second.first);
        it = pending.erase(it);
      } else {
        ++it;
      }
    }
  }

  void RequeueLocked(Task t) {
    t.failures++;
    if (t.failures > failure_max) {
      ++discarded;  // reference processFailedTask: discard permanently
    } else {
      todo.push_back(std::move(t));
    }
  }

  // status: 1 = task returned, 0 = none available now (pending outstanding),
  // 2 = task returned + new pass just started
  int GetTask(Task* out) {
    std::lock_guard<std::mutex> lk(mu);
    CheckTimeoutsLocked();
    bool new_pass = false;
    if (todo.empty()) {
      if (!pending.empty() || done.empty()) return 0;
      // all tasks done -> start the next pass (reference service.go GetTask)
      for (auto& t : done) {
        t.failures = 0;
        todo.push_back(std::move(t));
      }
      done.clear();
      ++pass;
      new_pass = true;
    }
    Task t = std::move(todo.front());
    todo.pop_front();
    pending[t.id] = {t, Clock::now()};
    *out = std::move(t);
    SnapshotLocked();
    return new_pass ? 2 : 1;
  }

  int TaskFinished(int64_t id) {
    std::lock_guard<std::mutex> lk(mu);
    auto it = pending.find(id);
    if (it == pending.end()) return 0;
    Task t = std::move(it->second.first);
    t.failures = 0;
    pending.erase(it);
    done.push_back(std::move(t));
    SnapshotLocked();
    return 1;
  }

  int TaskFailed(int64_t id) {
    std::lock_guard<std::mutex> lk(mu);
    auto it = pending.find(id);
    if (it == pending.end()) return 0;
    Task t = std::move(it->second.first);
    pending.erase(it);
    RequeueLocked(std::move(t));
    SnapshotLocked();
    return 1;
  }

  void Counts(int64_t* out) {
    std::lock_guard<std::mutex> lk(mu);
    CheckTimeoutsLocked();
    out[0] = (int64_t)todo.size();
    out[1] = (int64_t)pending.size();
    out[2] = (int64_t)done.size();
    out[3] = discarded;
    out[4] = pass;
  }

  // ---- TCP protocol ------------------------------------------------------
  // GET\n                     -> OK <status> <id>\n<chunk>\n...\n.\n | NONE\n
  // FIN <id>\n                -> OK\n | ERR\n
  // FAIL <id>\n               -> OK\n | ERR\n
  // SET <per_task> <n>\n<chunk>\n...  -> OK\n
  // INFO\n                    -> OK <todo> <pending> <done> <disc> <pass>\n
  int Serve(int port) {
    listen_fd = socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return -1;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons((uint16_t)port);
    if (bind(listen_fd, (sockaddr*)&addr, sizeof(addr)) < 0) {
      close(listen_fd);
      return -1;
    }
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd, (sockaddr*)&addr, &alen);
    int actual_port = ntohs(addr.sin_port);
    listen(listen_fd, 64);
    serving = true;
    server_thread = std::thread([this] { AcceptLoop(); });
    return actual_port;
  }

  void AcceptLoop() {
    while (serving) {
      int fd = accept(listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      std::lock_guard<std::mutex> lk(conn_mu);
      conn_threads.emplace_back([this, fd] { HandleConn(fd); });
    }
  }

  static bool ReadLine(int fd, std::string* line) {
    line->clear();
    char ch;
    for (;;) {
      ssize_t r = read(fd, &ch, 1);
      if (r <= 0) return false;
      if (ch == '\n') return true;
      line->push_back(ch);
    }
  }

  static void WriteAll(int fd, const std::string& s) {
    size_t off = 0;
    while (off < s.size()) {
      ssize_t w = write(fd, s.data() + off, s.size() - off);
      if (w <= 0) return;
      off += (size_t)w;
    }
  }

  void HandleConn(int fd) {
    std::string line;
    while (serving && ReadLine(fd, &line)) {
      std::istringstream is(line);
      std::string cmd;
      is >> cmd;
      if (cmd == "GET") {
        Task t;
        int st = GetTask(&t);
        if (st == 0) {
          WriteAll(fd, "NONE\n");
        } else {
          std::ostringstream os;
          os << "OK " << st << " " << t.id << "\n";
          for (auto& c : t.chunks) os << c << "\n";
          os << ".\n";
          WriteAll(fd, os.str());
        }
      } else if (cmd == "FIN" || cmd == "FAIL") {
        int64_t id;
        is >> id;
        int ok = cmd == "FIN" ? TaskFinished(id) : TaskFailed(id);
        WriteAll(fd, ok ? "OK\n" : "ERR\n");
      } else if (cmd == "SET") {
        size_t per_task, n;
        is >> per_task >> n;
        std::vector<std::string> chunks(n);
        bool good = true;
        for (auto& c : chunks) {
          if (!ReadLine(fd, &c)) {
            good = false;
            break;
          }
        }
        if (good) {
          SetDataset(chunks, per_task);
          WriteAll(fd, "OK\n");
        }
      } else if (cmd == "INFO") {
        int64_t c[5];
        Counts(c);
        std::ostringstream os;
        os << "OK " << c[0] << " " << c[1] << " " << c[2] << " " << c[3]
           << " " << c[4] << "\n";
        WriteAll(fd, os.str());
      } else {
        WriteAll(fd, "ERR unknown\n");
      }
    }
    close(fd);
  }

  void StopServe() {
    if (!serving.exchange(false)) return;
    shutdown(listen_fd, SHUT_RDWR);
    close(listen_fd);
    if (server_thread.joinable()) server_thread.join();
    std::lock_guard<std::mutex> lk(conn_mu);
    for (auto& t : conn_threads) {
      if (t.joinable()) t.join();
    }
    conn_threads.clear();
  }
};

}  // namespace

PT_API void* pt_master_create(int failure_max, double timeout_s,
                              const char* snapshot_path) {
  return new Master(failure_max, timeout_s, snapshot_path);
}

PT_API int pt_master_set_dataset(void* h, const char* const* chunks,
                                 size_t n, size_t chunks_per_task) {
  std::vector<std::string> v(chunks, chunks + n);
  return static_cast<Master*>(h)->SetDataset(v, chunks_per_task);
}

PT_API int pt_master_has_dataset(void* h) {
  std::lock_guard<std::mutex> lk(static_cast<Master*>(h)->mu);
  return static_cast<Master*>(h)->has_dataset ? 1 : 0;
}

// Returns status (0 none, 1 task, 2 task+new pass); fills id and writes
// newline-joined chunks into buf (truncated to buflen-1, NUL-terminated).
PT_API int pt_master_get_task(void* h, int64_t* id, char* buf,
                              size_t buflen) {
  Task t;
  int st = static_cast<Master*>(h)->GetTask(&t);
  if (st == 0) return 0;
  *id = t.id;
  std::string joined;
  for (size_t i = 0; i < t.chunks.size(); ++i) {
    if (i) joined += "\n";
    joined += t.chunks[i];
  }
  std::snprintf(buf, buflen, "%s", joined.c_str());
  return st;
}

PT_API int pt_master_task_finished(void* h, int64_t id) {
  return static_cast<Master*>(h)->TaskFinished(id);
}

PT_API int pt_master_task_failed(void* h, int64_t id) {
  return static_cast<Master*>(h)->TaskFailed(id);
}

// out: [todo, pending, done, discarded, pass]
PT_API void pt_master_counts(void* h, int64_t* out) {
  static_cast<Master*>(h)->Counts(out);
}

PT_API int pt_master_serve(void* h, int port) {
  return static_cast<Master*>(h)->Serve(port);
}

PT_API void pt_master_stop(void* h) { static_cast<Master*>(h)->StopServe(); }

PT_API void pt_master_destroy(void* h) { delete static_cast<Master*>(h); }
