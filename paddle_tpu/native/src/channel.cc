// Go-style CSP channels carrying fixed-size elements.
//
// Reference: /root/reference/paddle/fluid/framework/channel.h:24,42
// (MakeChannel / Channel<T>), details/buffered_channel.h (bounded queue with
// send/recv condition variables) and details/unbuffered_channel.h (rendezvous
// handoff).  Semantics preserved here:
//   * capacity > 0  -> buffered: send blocks while full, recv blocks while
//     empty.
//   * capacity == 0 -> unbuffered: send blocks until a receiver has taken the
//     element (rendezvous).
//   * close() wakes all waiters; recv drains remaining buffered elements and
//     then fails; send on a closed channel fails.
#include "common.h"

#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

namespace {

struct Channel {
  size_t elem_size;
  size_t capacity;  // 0 = unbuffered rendezvous
  std::deque<std::vector<char>> buf;
  uint64_t pushed = 0;   // total elements ever enqueued
  uint64_t popped = 0;   // total elements ever dequeued
  bool closed = false;
  std::mutex mu;
  std::condition_variable not_full;   // buffered senders wait here
  std::condition_variable not_empty;  // receivers wait here
  std::condition_variable consumed;   // unbuffered senders wait here
};

}  // namespace

PT_API void* pt_channel_create(size_t elem_size, size_t capacity) {
  auto* c = new Channel();
  c->elem_size = elem_size;
  c->capacity = capacity;
  return c;
}

PT_API int pt_channel_send(void* h, const void* data) {
  auto* c = static_cast<Channel*>(h);
  std::unique_lock<std::mutex> lk(c->mu);
  if (c->capacity > 0) {
    c->not_full.wait(
        lk, [&] { return c->closed || c->buf.size() < c->capacity; });
    if (c->closed) return 0;
    c->buf.emplace_back(static_cast<const char*>(data),
                        static_cast<const char*>(data) + c->elem_size);
    ++c->pushed;
    c->not_empty.notify_one();
    return 1;
  }
  // Unbuffered: enqueue, then wait until a receiver has dequeued our element.
  // FIFO order means our element is gone once popped reaches our sequence no.
  if (c->closed) return 0;
  c->buf.emplace_back(static_cast<const char*>(data),
                      static_cast<const char*>(data) + c->elem_size);
  uint64_t myseq = ++c->pushed;
  c->not_empty.notify_one();
  c->consumed.wait(lk, [&] { return c->closed || c->popped >= myseq; });
  return c->popped >= myseq ? 1 : 0;
}

PT_API int pt_channel_recv(void* h, void* out) {
  auto* c = static_cast<Channel*>(h);
  std::unique_lock<std::mutex> lk(c->mu);
  c->not_empty.wait(lk, [&] { return c->closed || !c->buf.empty(); });
  if (c->buf.empty()) return 0;  // closed and fully drained
  std::memcpy(out, c->buf.front().data(), c->elem_size);
  c->buf.pop_front();
  ++c->popped;
  if (c->capacity > 0) {
    c->not_full.notify_one();
  } else {
    c->consumed.notify_all();
  }
  return 1;
}

PT_API void pt_channel_close(void* h) {
  auto* c = static_cast<Channel*>(h);
  {
    std::lock_guard<std::mutex> lk(c->mu);
    c->closed = true;
    // Unbuffered: pending elements belong to senders that will now report
    // failure — drop them so a message is never both "not sent" and
    // delivered.  (Buffered elements were successfully sent; recv drains
    // them, matching the reference's buffered_channel close semantics.)
    if (c->capacity == 0) c->buf.clear();
  }
  c->not_full.notify_all();
  c->not_empty.notify_all();
  c->consumed.notify_all();
}

PT_API size_t pt_channel_size(void* h) {
  auto* c = static_cast<Channel*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  return c->buf.size();
}

PT_API int pt_channel_is_closed(void* h) {
  auto* c = static_cast<Channel*>(h);
  std::lock_guard<std::mutex> lk(c->mu);
  return c->closed ? 1 : 0;
}

PT_API void pt_channel_destroy(void* h) { delete static_cast<Channel*>(h); }
