// Fixed-size worker pool with a shared task queue and a wait-all barrier.
//
// Reference: /root/reference/paddle/fluid/framework/threadpool.h (ThreadPool
// singleton used by parallel_do and async ops; Run/Wait interface).  Used
// internally by the native data-loader pipeline and exposed over the C ABI
// for host-side parallel work.
#include "common.h"

#include <condition_variable>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct ThreadPool {
  std::vector<std::thread> workers;
  std::deque<std::function<void()>> tasks;
  std::mutex mu;
  std::condition_variable cv;       // workers wait for tasks
  std::condition_variable idle_cv;  // Wait() blocks until drained
  size_t active = 0;
  bool stop = false;

  explicit ThreadPool(size_t n) {
    workers.reserve(n);
    for (size_t i = 0; i < n; ++i) {
      workers.emplace_back([this] { Loop(); });
    }
  }

  void Loop() {
    for (;;) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return stop || !tasks.empty(); });
        if (stop && tasks.empty()) return;
        task = std::move(tasks.front());
        tasks.pop_front();
        ++active;
      }
      task();
      {
        std::lock_guard<std::mutex> lk(mu);
        --active;
        if (tasks.empty() && active == 0) idle_cv.notify_all();
      }
    }
  }

  void Submit(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lk(mu);
      tasks.push_back(std::move(fn));
    }
    cv.notify_one();
  }

  void Wait() {
    std::unique_lock<std::mutex> lk(mu);
    idle_cv.wait(lk, [&] { return tasks.empty() && active == 0; });
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv.notify_all();
    for (auto& w : workers) w.join();
  }
};

}  // namespace

PT_API void* pt_threadpool_create(size_t num_threads) {
  if (num_threads == 0) {
    num_threads = std::max(1u, std::thread::hardware_concurrency());
  }
  return new ThreadPool(num_threads);
}

PT_API size_t pt_threadpool_num_threads(void* h) {
  return static_cast<ThreadPool*>(h)->workers.size();
}

typedef void (*pt_task_fn)(void*);

PT_API void pt_threadpool_submit(void* h, pt_task_fn fn, void* arg) {
  static_cast<ThreadPool*>(h)->Submit([fn, arg] { fn(arg); });
}

PT_API void pt_threadpool_wait(void* h) {
  static_cast<ThreadPool*>(h)->Wait();
}

PT_API void pt_threadpool_destroy(void* h) {
  delete static_cast<ThreadPool*>(h);
}
