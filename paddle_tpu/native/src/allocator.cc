// Power-of-two buddy allocator over malloc'd arena chunks.
//
// Reference: /root/reference/paddle/fluid/memory/detail/buddy_allocator.h:33
// and system_allocator.cc — the reference manages GPU/pinned-host memory with
// a buddy system (split on alloc, coalesce buddies on free, fall back to the
// system allocator for oversize requests).  On TPU the device heap belongs to
// XLA, so this allocator serves the host side: pinned staging buffers for the
// native data-loader pipeline and any runtime service needing cheap recycled
// buffers without malloc churn.
//
// Design: headerless buddy with external metadata.  Arena chunks of
// 1<<chunk_log2 bytes are obtained from aligned_alloc; free blocks live in
// per-level free lists keyed by byte offset inside their chunk, so the buddy
// of a block at offset o on level L is simply o ^ (1<<L).  Requests larger
// than a chunk go straight to the system allocator ("huge" path), mirroring
// the reference's fallback.
#include "common.h"

#include <cstdlib>
#include <map>
#include <mutex>
#include <set>
#include <unordered_map>
#include <vector>

namespace {

struct Chunk {
  char* base;
};

struct BuddyAllocator {
  size_t min_log2;    // smallest block: 1<<min_log2 bytes
  size_t chunk_log2;  // arena chunk: 1<<chunk_log2 bytes
  std::mutex mu;
  // chunk base address -> chunk record, ordered so we can find the chunk
  // containing any pointer with upper_bound.
  std::map<char*, Chunk> chunks;
  // free_lists[level] = set of free block addresses of size 1<<level
  std::vector<std::set<char*>> free_lists;
  // allocated block -> level
  std::unordered_map<void*, size_t> allocated;
  // oversize allocations served directly by malloc: ptr -> size
  std::unordered_map<void*, size_t> huge;
  // stats (bytes)
  uint64_t arena_bytes = 0;
  uint64_t in_use = 0;
  uint64_t peak_in_use = 0;

  BuddyAllocator(size_t min_l, size_t chunk_l)
      : min_log2(min_l), chunk_log2(chunk_l), free_lists(chunk_l + 1) {}

  ~BuddyAllocator() {
    for (auto& kv : chunks) std::free(kv.first);
    for (auto& kv : huge) std::free(kv.first);
  }

  size_t LevelFor(size_t n) const {
    size_t level = min_log2;
    while ((size_t(1) << level) < n) ++level;
    return level;
  }

  char* ChunkBaseOf(char* p) const {
    auto it = chunks.upper_bound(p);
    --it;  // largest base <= p; caller guarantees p is inside some chunk
    return it->first;
  }

  void* Alloc(size_t n) {
    if (n == 0) n = 1;
    std::lock_guard<std::mutex> lk(mu);
    if (n > (size_t(1) << chunk_log2)) {
      void* p = std::malloc(n);
      if (!p) return nullptr;
      huge[p] = n;
      in_use += n;
      arena_bytes += n;
      if (in_use > peak_in_use) peak_in_use = in_use;
      return p;
    }
    size_t level = LevelFor(n);
    // find the lowest level >= `level` with a free block
    size_t l = level;
    while (l <= chunk_log2 && free_lists[l].empty()) ++l;
    if (l > chunk_log2) {
      char* base =
          static_cast<char*>(std::aligned_alloc(4096, size_t(1) << chunk_log2));
      if (!base) return nullptr;
      chunks[base] = Chunk{base};
      arena_bytes += size_t(1) << chunk_log2;
      free_lists[chunk_log2].insert(base);
      l = chunk_log2;
    }
    char* block = *free_lists[l].begin();
    free_lists[l].erase(free_lists[l].begin());
    // split down to the requested level, freeing the upper buddy each time
    while (l > level) {
      --l;
      free_lists[l].insert(block + (size_t(1) << l));
    }
    allocated[block] = level;
    in_use += size_t(1) << level;
    if (in_use > peak_in_use) peak_in_use = in_use;
    return block;
  }

  void Free(void* p) {
    if (!p) return;
    std::lock_guard<std::mutex> lk(mu);
    auto hit = huge.find(p);
    if (hit != huge.end()) {
      in_use -= hit->second;
      arena_bytes -= hit->second;
      std::free(p);
      huge.erase(hit);
      return;
    }
    auto it = allocated.find(p);
    if (it == allocated.end()) return;  // double free / foreign pointer: no-op
    size_t level = it->second;
    allocated.erase(it);
    in_use -= size_t(1) << level;
    char* block = static_cast<char*>(p);
    char* base = ChunkBaseOf(block);
    // coalesce with free buddies as far up as possible
    while (level < chunk_log2) {
      size_t offset = size_t(block - base);
      char* buddy = base + (offset ^ (size_t(1) << level));
      auto& fl = free_lists[level];
      auto bit = fl.find(buddy);
      if (bit == fl.end()) break;
      fl.erase(bit);
      if (buddy < block) block = buddy;
      ++level;
    }
    free_lists[level].insert(block);
  }
};

}  // namespace

// Internal C++ access for sibling translation units (loader.cc).
void* pt_internal_buddy_create(size_t min_log2, size_t chunk_log2) {
  return new BuddyAllocator(min_log2, chunk_log2);
}
void* pt_internal_buddy_alloc(void* h, size_t n) {
  return static_cast<BuddyAllocator*>(h)->Alloc(n);
}
void pt_internal_buddy_free(void* h, void* p) {
  static_cast<BuddyAllocator*>(h)->Free(p);
}
void pt_internal_buddy_destroy(void* h) {
  delete static_cast<BuddyAllocator*>(h);
}

PT_API void* pt_buddy_create(size_t min_log2, size_t chunk_log2) {
  if (min_log2 == 0) min_log2 = 6;     // 64 B
  if (chunk_log2 == 0) chunk_log2 = 26;  // 64 MiB
  if (chunk_log2 < min_log2) chunk_log2 = min_log2;
  return new BuddyAllocator(min_log2, chunk_log2);
}

PT_API void* pt_buddy_alloc(void* h, size_t n) {
  return static_cast<BuddyAllocator*>(h)->Alloc(n);
}

PT_API void pt_buddy_free(void* h, void* p) {
  static_cast<BuddyAllocator*>(h)->Free(p);
}

// out: [arena_bytes, in_use, peak_in_use, num_chunks]
PT_API void pt_buddy_stats(void* h, uint64_t* out) {
  auto* a = static_cast<BuddyAllocator*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  out[0] = a->arena_bytes;
  out[1] = a->in_use;
  out[2] = a->peak_in_use;
  out[3] = a->chunks.size() + a->huge.size();
}

PT_API void pt_buddy_destroy(void* h) {
  delete static_cast<BuddyAllocator*>(h);
}
