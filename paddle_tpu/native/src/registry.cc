// TTL-lease service registry: elastic pserver membership + liveness.
//
// Reference: go/pserver/etcd_client.go — a pserver registers under the
// lowest free index below the desired count with a TTL lease kept alive by
// heartbeats (Register :40-120), publishing its address for trainer-side
// discovery (go/pserver/client/etcd_client.go); an expired lease frees the
// index so a replacement can claim it, which is the failover story
// (go/cmd/pserver/pserver.go:34-45).  The TPU rebuild replaces the external
// etcd dependency with this in-tree native service: same lease semantics,
// lazy expiry on access (the master.cc timeout idiom), served in-process
// via the C ABI and over a line-oriented TCP protocol for multi-process
// clusters.
#include "common.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Entry {
  std::string addr;
  int64_t lease = 0;
  double ttl_s = 0;
  Clock::time_point renewed;
};

struct Registry {
  std::mutex mu;
  std::condition_variable cv;
  // kind -> (index -> entry); kind -> desired count (0 = unbounded)
  std::map<std::string, std::map<int, Entry>> kinds;
  std::map<std::string, int> desired;
  int64_t next_lease = 1;

  // TCP server.  Connection threads are DETACHED (a registry serves an
  // unbounded stream of short-lived heartbeat connections; keeping one
  // joinable std::thread per finished connection would grow without
  // bound); open fds are tracked so StopServe can shutdown() them, which
  // unblocks any thread parked in read().
  std::atomic<bool> serving{false};
  // set (under mu) by StopServe so handlers parked in WaitReady's cv —
  // which fd shutdown cannot unblock — wake and exit before teardown
  std::atomic<bool> stopping{false};
  std::atomic<int> active_conns{0};
  int listen_fd = -1;
  std::thread server_thread;
  std::set<int> conn_fds;
  std::mutex conn_mu;

  ~Registry() { StopServe(); }

  void ExpireLocked(const std::string& kind) {
    auto it = kinds.find(kind);
    if (it == kinds.end()) return;
    auto now = Clock::now();
    for (auto e = it->second.begin(); e != it->second.end();) {
      double age =
          std::chrono::duration<double>(now - e->second.renewed).count();
      if (age > e->second.ttl_s) {
        e = it->second.erase(e);  // lease expired -> index is free again
      } else {
        ++e;
      }
    }
  }

  void SetDesired(const std::string& kind, int n) {
    std::lock_guard<std::mutex> lk(mu);
    desired[kind] = n;
  }

  // Assign the LOWEST free index (reference etcd_client.go Register scans
  // slots 0..desired-1).  Returns index >= 0 and sets *lease, or -1 when
  // every slot below the desired count is held by a live lease.
  int Register(const std::string& kind, const std::string& addr,
               double ttl_s, int64_t* lease) {
    std::lock_guard<std::mutex> lk(mu);
    ExpireLocked(kind);
    auto& slots = kinds[kind];
    int limit = desired.count(kind) ? desired[kind] : 0;
    int idx = 0;
    for (;; ++idx) {
      if (limit > 0 && idx >= limit) return -1;
      if (!slots.count(idx)) break;
    }
    Entry e;
    e.addr = addr;
    e.ttl_s = ttl_s;
    e.lease = next_lease++;
    e.renewed = Clock::now();
    *lease = e.lease;
    slots[idx] = std::move(e);
    cv.notify_all();
    return idx;
  }

  // 1 = renewed; 0 = lease lost (expired and possibly re-assigned)
  int Heartbeat(const std::string& kind, int index, int64_t lease) {
    std::lock_guard<std::mutex> lk(mu);
    ExpireLocked(kind);
    auto kit = kinds.find(kind);
    if (kit == kinds.end()) return 0;
    auto it = kit->second.find(index);
    if (it == kit->second.end() || it->second.lease != lease) return 0;
    it->second.renewed = Clock::now();
    return 1;
  }

  int Deregister(const std::string& kind, int index, int64_t lease) {
    std::lock_guard<std::mutex> lk(mu);
    auto kit = kinds.find(kind);
    if (kit == kinds.end()) return 0;
    auto it = kit->second.find(index);
    if (it == kit->second.end() || it->second.lease != lease) return 0;
    kit->second.erase(it);
    cv.notify_all();
    return 1;
  }

  // newline-joined "<index> <addr>" lines for live entries
  std::string List(const std::string& kind) {
    std::lock_guard<std::mutex> lk(mu);
    ExpireLocked(kind);
    std::ostringstream os;
    auto kit = kinds.find(kind);
    if (kit != kinds.end()) {
      for (auto& kv : kit->second) {
        os << kv.first << " " << kv.second.addr << "\n";
      }
    }
    return os.str();
  }

  // block until `n` live entries of `kind` (1) or timeout (0)
  int WaitReady(const std::string& kind, size_t n, double timeout_s) {
    std::unique_lock<std::mutex> lk(mu);
    auto deadline = Clock::now() +
        std::chrono::duration_cast<Clock::duration>(
            std::chrono::duration<double>(timeout_s));
    for (;;) {
      if (stopping) return 0;  // registry is tearing down
      ExpireLocked(kind);
      if (kinds[kind].size() >= n) return 1;
      // re-check at least every 50ms: expiry is lazy, so a waiter must
      // poll even without notifications
      auto tick = Clock::now() + std::chrono::milliseconds(50);
      auto until = tick < deadline ? tick : deadline;
      if (cv.wait_until(lk, until) == std::cv_status::timeout &&
          Clock::now() >= deadline) {
        ExpireLocked(kind);
        return kinds[kind].size() >= n ? 1 : 0;
      }
    }
  }

  // ---- TCP protocol ------------------------------------------------------
  // DESIRE <kind> <n>\n                -> OK\n
  // REG <kind> <ttl_ms> <addr>\n       -> OK <index> <lease>\n | FULL\n
  // HB <kind> <index> <lease>\n        -> OK\n | GONE\n
  // DEREG <kind> <index> <lease>\n     -> OK\n | GONE\n
  // LIST <kind>\n                      -> OK\n<index> <addr>\n... .\n
  // WAIT <kind> <n> <timeout_ms>\n     -> OK\n | TIMEOUT\n
  int Serve(int port) {
    listen_fd = socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return -1;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons((uint16_t)port);
    if (bind(listen_fd, (sockaddr*)&addr, sizeof(addr)) < 0) {
      close(listen_fd);
      return -1;
    }
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd, (sockaddr*)&addr, &alen);
    int actual_port = ntohs(addr.sin_port);
    listen(listen_fd, 64);
    serving = true;
    server_thread = std::thread([this] { AcceptLoop(); });
    return actual_port;
  }

  void AcceptLoop() {
    while (serving) {
      int fd = accept(listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      {
        std::lock_guard<std::mutex> lk(conn_mu);
        if (!serving) {  // raced with StopServe: don't leak the fd
          close(fd);
          continue;
        }
        conn_fds.insert(fd);
      }
      ++active_conns;
      std::thread([this, fd] { HandleConn(fd); }).detach();
    }
  }

  static bool ReadLine(int fd, std::string* line) {
    line->clear();
    char ch;
    for (;;) {
      ssize_t r = read(fd, &ch, 1);
      if (r <= 0) return false;
      if (ch == '\n') return true;
      line->push_back(ch);
    }
  }

  static void WriteAll(int fd, const std::string& s) {
    size_t off = 0;
    while (off < s.size()) {
      ssize_t w = write(fd, s.data() + off, s.size() - off);
      if (w <= 0) return;
      off += (size_t)w;
    }
  }

  void HandleConn(int fd) {
    std::string line;
    while (serving && ReadLine(fd, &line)) {
      std::istringstream is(line);
      std::string cmd, kind;
      is >> cmd;
      if (cmd == "DESIRE") {
        int n;
        is >> kind >> n;
        SetDesired(kind, n);
        WriteAll(fd, "OK\n");
      } else if (cmd == "REG") {
        int64_t ttl_ms;
        std::string addr;
        is >> kind >> ttl_ms >> addr;
        int64_t lease = 0;
        int idx = Register(kind, addr, ttl_ms / 1000.0, &lease);
        if (idx < 0) {
          WriteAll(fd, "FULL\n");
        } else {
          std::ostringstream os;
          os << "OK " << idx << " " << lease << "\n";
          WriteAll(fd, os.str());
        }
      } else if (cmd == "HB" || cmd == "DEREG") {
        int index;
        int64_t lease;
        is >> kind >> index >> lease;
        int ok = cmd == "HB" ? Heartbeat(kind, index, lease)
                             : Deregister(kind, index, lease);
        WriteAll(fd, ok ? "OK\n" : "GONE\n");
      } else if (cmd == "LIST") {
        is >> kind;
        WriteAll(fd, "OK\n" + List(kind) + ".\n");
      } else if (cmd == "WAIT") {
        size_t n;
        int64_t timeout_ms;
        is >> kind >> n >> timeout_ms;
        int ok = WaitReady(kind, n, timeout_ms / 1000.0);
        WriteAll(fd, ok ? "OK\n" : "TIMEOUT\n");
      } else {
        WriteAll(fd, "ERR\n");
      }
    }
    {
      std::lock_guard<std::mutex> lk(conn_mu);
      conn_fds.erase(fd);
    }
    close(fd);
    --active_conns;
  }

  void StopServe() {
    if (!serving.exchange(false)) return;
    {
      // under mu so a WaitReady between its stopping-check and cv.wait
      // cannot miss the wakeup
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv.notify_all();
    shutdown(listen_fd, SHUT_RDWR);
    close(listen_fd);
    if (server_thread.joinable()) server_thread.join();
    {
      // unblock handler threads parked in read() on idle clients
      std::lock_guard<std::mutex> lk(conn_mu);
      for (int fd : conn_fds) shutdown(fd, SHUT_RDWR);
    }
    // wait until every detached handler has actually exited before
    // returning: the caller (pt_registry_destroy / ~Registry) deletes
    // this object next, so returning with a live handler would be a
    // use-after-free.  Handlers in read() are woken by the fd shutdown
    // above, handlers in WaitReady by stopping+notify_all; re-notify in
    // the loop in case one re-entered the cv before seeing the flag.
    // A generous deadline guards the must-wait: a handler stuck in a
    // syscall the fd shutdown cannot interrupt would otherwise spin this
    // loop forever with no diagnostic.  Returning with a live handler is
    // a use-after-free, so past the deadline we report and abort instead
    // of silently hanging or corrupting memory.
    auto deadline = Clock::now() + std::chrono::seconds(30);
    while (active_conns.load() > 0) {
      cv.notify_all();
      if (Clock::now() > deadline) {
        if (active_conns.load() == 0) break;  // exited during this tick
        std::fprintf(stderr,
                     "pt_registry: StopServe timed out after 30s with %d "
                     "handler thread(s) stuck; aborting to avoid "
                     "use-after-free\n",
                     active_conns.load());
        std::abort();
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(5));
    }
    // every handler has exited: clear the flag so the in-process
    // WaitReady API and a later Serve() on this handle work again
    stopping = false;
  }
};

}  // namespace

PT_API void* pt_registry_create() { return new Registry(); }

PT_API void pt_registry_set_desired(void* h, const char* kind, int n) {
  static_cast<Registry*>(h)->SetDesired(kind, n);
}

PT_API int pt_registry_register(void* h, const char* kind, const char* addr,
                                double ttl_s, int64_t* lease) {
  return static_cast<Registry*>(h)->Register(kind, addr, ttl_s, lease);
}

PT_API int pt_registry_heartbeat(void* h, const char* kind, int index,
                                 int64_t lease) {
  return static_cast<Registry*>(h)->Heartbeat(kind, index, lease);
}

PT_API int pt_registry_deregister(void* h, const char* kind, int index,
                                  int64_t lease) {
  return static_cast<Registry*>(h)->Deregister(kind, index, lease);
}

// writes newline-joined "<index> <addr>" into buf (NUL-terminated)
// Returns the REQUIRED length (strlen, excluding NUL).  A return >=
// buflen means the copy was truncated and the caller must retry with a
// bigger buffer — silent truncation would drop live endpoints.
PT_API size_t pt_registry_list(void* h, const char* kind, char* buf,
                               size_t buflen) {
  std::string s = static_cast<Registry*>(h)->List(kind);
  std::snprintf(buf, buflen, "%s", s.c_str());
  return s.size();
}

PT_API int pt_registry_wait_ready(void* h, const char* kind, size_t n,
                                  double timeout_s) {
  return static_cast<Registry*>(h)->WaitReady(kind, n, timeout_s);
}

PT_API int pt_registry_serve(void* h, int port) {
  return static_cast<Registry*>(h)->Serve(port);
}

PT_API void pt_registry_stop(void* h) {
  static_cast<Registry*>(h)->StopServe();
}

PT_API void pt_registry_destroy(void* h) {
  delete static_cast<Registry*>(h);
}
