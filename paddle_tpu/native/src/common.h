// Shared declarations for the native runtime library.
//
// The reference implements its runtime layer (channels, thread pool, memory
// allocator, reader pipeline, cloud master) in native code
// (/root/reference/paddle/fluid/framework/channel.h, threadpool.h,
// memory/detail/buddy_allocator.h, framework/reader.h, go/master/service.go).
// This library is the TPU rebuild's native equivalent: host-side runtime
// services around the JAX/XLA compute path, exposed to Python over a flat
// C ABI consumed via ctypes.
#pragma once
#include <cstddef>
#include <cstdint>

#define PT_API extern "C" __attribute__((visibility("default")))
