"""Native runtime library: C++ host-side services behind a ctypes C ABI.

The reference's runtime layer is native C++ (channels framework/channel.h,
thread pool framework/threadpool.h, buddy allocator
memory/detail/buddy_allocator.h, reader pipeline framework/reader.h, cloud
master go/master/service.go).  This package is the TPU rebuild's native
equivalent, compiled on first use with the local toolchain (g++) into
``_native.so`` and loaded via ctypes.  JAX/XLA owns the device; this layer
owns host concurrency, staging memory, data loading and cluster services.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_native.so")
_lock = threading.Lock()
_lib = None


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    src = os.path.join(_DIR, "src")
    return any(
        os.path.getmtime(os.path.join(src, f)) > so_mtime
        for f in os.listdir(src)
        if f.endswith((".cc", ".h"))
    )


def _build():
    try:
        subprocess.run(
            ["make", "-s"], cwd=_DIR, check=True, capture_output=True
        )
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        out = getattr(e, "stderr", b"") or b""
        raise RuntimeError(
            "failed to build paddle_tpu native library: %s" % out.decode()
        ) from e


def lib() -> ctypes.CDLL:
    """Build (if stale) and load the native library."""
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _needs_build():
            _build()
        l = ctypes.CDLL(_SO)
        _declare(l)
        _lib = l
    return _lib


def _declare(l: ctypes.CDLL):
    p, sz, i, u64 = (
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64),
    )
    l.pt_channel_create.restype = p
    l.pt_channel_create.argtypes = [sz, sz]
    l.pt_channel_send.restype = i
    l.pt_channel_send.argtypes = [p, ctypes.c_void_p]
    l.pt_channel_recv.restype = i
    l.pt_channel_recv.argtypes = [p, ctypes.c_void_p]
    l.pt_channel_close.argtypes = [p]
    l.pt_channel_size.restype = sz
    l.pt_channel_size.argtypes = [p]
    l.pt_channel_is_closed.restype = i
    l.pt_channel_is_closed.argtypes = [p]
    l.pt_channel_destroy.argtypes = [p]

    l.pt_threadpool_create.restype = p
    l.pt_threadpool_create.argtypes = [sz]
    l.pt_threadpool_num_threads.restype = sz
    l.pt_threadpool_num_threads.argtypes = [p]
    l.pt_threadpool_submit.argtypes = [p, ctypes.c_void_p, ctypes.c_void_p]
    l.pt_threadpool_wait.argtypes = [p]
    l.pt_threadpool_destroy.argtypes = [p]

    l.pt_buddy_create.restype = p
    l.pt_buddy_create.argtypes = [sz, sz]
    l.pt_buddy_alloc.restype = p
    l.pt_buddy_alloc.argtypes = [p, sz]
    l.pt_buddy_free.argtypes = [p, ctypes.c_void_p]
    l.pt_buddy_stats.argtypes = [p, u64]
    l.pt_buddy_destroy.argtypes = [p]


TASK_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


class Channel:
    """CSP channel of fixed-size byte elements (capacity 0 = unbuffered).

    Mirrors the reference's Channel semantics (framework/channel.h): blocking
    send/recv, close() wakes waiters, recv drains a closed channel.
    """

    def __init__(self, elem_size: int, capacity: int = 0):
        self._l = lib()
        self.elem_size = elem_size
        self._h = self._l.pt_channel_create(elem_size, capacity)

    def send(self, data: bytes) -> bool:
        if len(data) != self.elem_size:
            raise ValueError(
                f"element must be {self.elem_size} bytes, got {len(data)}"
            )
        buf = ctypes.create_string_buffer(data, self.elem_size)
        return bool(self._l.pt_channel_send(self._h, ctypes.cast(buf, ctypes.c_void_p)))

    def recv(self):
        buf = ctypes.create_string_buffer(self.elem_size)
        ok = self._l.pt_channel_recv(self._h, ctypes.cast(buf, ctypes.c_void_p))
        return buf.raw if ok else None

    def close(self):
        self._l.pt_channel_close(self._h)

    def __len__(self):
        return self._l.pt_channel_size(self._h)

    @property
    def closed(self) -> bool:
        return bool(self._l.pt_channel_is_closed(self._h))

    def __del__(self):
        if getattr(self, "_h", None):
            self._l.pt_channel_destroy(self._h)
            self._h = None


class ThreadPool:
    """Native worker pool (reference framework/threadpool.h)."""

    def __init__(self, num_threads: int = 0):
        self._l = lib()
        self._h = self._l.pt_threadpool_create(num_threads)
        self._keepalive = []

    @property
    def num_threads(self) -> int:
        return self._l.pt_threadpool_num_threads(self._h)

    def submit(self, fn):
        """Run zero-arg python callable on a pool thread."""
        cb_holder = []

        def trampoline(_):
            try:
                fn()
            finally:
                self._keepalive.remove(cb_holder[0])

        cb = TASK_FN(trampoline)
        cb_holder.append(cb)
        self._keepalive.append(cb)
        self._l.pt_threadpool_submit(
            self._h, ctypes.cast(cb, ctypes.c_void_p), None
        )

    def wait(self):
        self._l.pt_threadpool_wait(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._l.pt_threadpool_destroy(self._h)
            self._h = None


class BuddyAllocator:
    """Buddy-system host allocator (reference memory/detail/buddy_allocator.h).

    alloc() returns raw addresses (ints) inside native arena chunks; use
    with `view()` to get zero-copy numpy arrays over allocator memory.
    """

    def __init__(self, min_block_log2: int = 6, chunk_log2: int = 26):
        self._l = lib()
        self._h = self._l.pt_buddy_create(min_block_log2, chunk_log2)

    def alloc(self, nbytes: int) -> int:
        p = self._l.pt_buddy_alloc(self._h, nbytes)
        if not p:
            raise MemoryError(f"buddy allocator failed for {nbytes} bytes")
        return p

    def free(self, addr: int):
        self._l.pt_buddy_free(self._h, addr)

    def view(self, addr: int, shape, dtype):
        import numpy as np

        dtype = np.dtype(dtype)
        n = int(np.prod(shape)) * dtype.itemsize
        buf = (ctypes.c_char * n).from_address(addr)
        return np.frombuffer(buf, dtype=dtype).reshape(shape)

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 4)()
        self._l.pt_buddy_stats(self._h, out)
        return {
            "arena_bytes": out[0],
            "in_use": out[1],
            "peak_in_use": out[2],
            "num_chunks": out[3],
        }

    def __del__(self):
        if getattr(self, "_h", None):
            self._l.pt_buddy_destroy(self._h)
            self._h = None
