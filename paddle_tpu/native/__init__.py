"""Native runtime library: C++ host-side services behind a ctypes C ABI.

The reference's runtime layer is native C++ (channels framework/channel.h,
thread pool framework/threadpool.h, buddy allocator
memory/detail/buddy_allocator.h, reader pipeline framework/reader.h, cloud
master go/master/service.go).  This package is the TPU rebuild's native
equivalent, compiled on first use with the local toolchain (g++) into
``_native.so`` and loaded via ctypes.  JAX/XLA owns the device; this layer
owns host concurrency, staging memory, data loading and cluster services.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_native.so")
_lock = threading.Lock()
_lib = None


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    src = os.path.join(_DIR, "src")
    return any(
        os.path.getmtime(os.path.join(src, f)) > so_mtime
        for f in os.listdir(src)
        if f.endswith((".cc", ".h"))
    )


def _build():
    try:
        subprocess.run(
            ["make", "-s"], cwd=_DIR, check=True, capture_output=True
        )
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        out = getattr(e, "stderr", b"") or b""
        raise RuntimeError(
            "failed to build paddle_tpu native library: %s" % out.decode()
        ) from e


def lib() -> ctypes.CDLL:
    """Build (if stale) and load the native library."""
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _needs_build():
            # lint: blocking-under-lock-ok — the subprocess IS the
            # critical section: one first-caller compiles the .so while
            # every other thread must wait for exactly that build
            _build()
        l = ctypes.CDLL(_SO)
        _declare(l)
        _lib = l
    return _lib


def _declare(l: ctypes.CDLL):
    p, sz, i, u64 = (
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64),
    )
    l.pt_channel_create.restype = p
    l.pt_channel_create.argtypes = [sz, sz]
    l.pt_channel_send.restype = i
    l.pt_channel_send.argtypes = [p, ctypes.c_void_p]
    l.pt_channel_recv.restype = i
    l.pt_channel_recv.argtypes = [p, ctypes.c_void_p]
    l.pt_channel_close.argtypes = [p]
    l.pt_channel_size.restype = sz
    l.pt_channel_size.argtypes = [p]
    l.pt_channel_is_closed.restype = i
    l.pt_channel_is_closed.argtypes = [p]
    l.pt_channel_destroy.argtypes = [p]

    l.pt_threadpool_create.restype = p
    l.pt_threadpool_create.argtypes = [sz]
    l.pt_threadpool_num_threads.restype = sz
    l.pt_threadpool_num_threads.argtypes = [p]
    l.pt_threadpool_submit.argtypes = [p, ctypes.c_void_p, ctypes.c_void_p]
    l.pt_threadpool_wait.argtypes = [p]
    l.pt_threadpool_destroy.argtypes = [p]

    l.pt_buddy_create.restype = p
    l.pt_buddy_create.argtypes = [sz, sz]
    l.pt_buddy_alloc.restype = p
    l.pt_buddy_alloc.argtypes = [p, sz]
    l.pt_buddy_free.argtypes = [p, ctypes.c_void_p]
    l.pt_buddy_stats.argtypes = [p, u64]
    l.pt_buddy_destroy.argtypes = [p]

    l.pt_loader_create.restype = p
    l.pt_loader_create.argtypes = [
        sz, ctypes.POINTER(ctypes.c_size_t), sz, sz, ctypes.c_uint64, sz, i,
    ]
    l.pt_loader_push.restype = i
    l.pt_loader_push.argtypes = [p, ctypes.POINTER(ctypes.c_void_p)]
    l.pt_loader_finish_epoch.argtypes = [p]
    l.pt_loader_next.restype = p
    l.pt_loader_next.argtypes = [p]
    l.pt_batch_n.restype = sz
    l.pt_batch_n.argtypes = [p]
    l.pt_batch_slot.restype = p
    l.pt_batch_slot.argtypes = [p, sz]
    l.pt_batch_release.argtypes = [p, p]
    l.pt_loader_destroy.argtypes = [p]


TASK_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


class Channel:
    """CSP channel of fixed-size byte elements (capacity 0 = unbuffered).

    Mirrors the reference's Channel semantics (framework/channel.h): blocking
    send/recv, close() wakes waiters, recv drains a closed channel.
    """

    def __init__(self, elem_size: int, capacity: int = 0):
        self._l = lib()
        self.elem_size = elem_size
        self._h = self._l.pt_channel_create(elem_size, capacity)

    def send(self, data: bytes) -> bool:
        if len(data) != self.elem_size:
            raise ValueError(
                f"element must be {self.elem_size} bytes, got {len(data)}"
            )
        buf = ctypes.create_string_buffer(data, self.elem_size)
        return bool(self._l.pt_channel_send(self._h, ctypes.cast(buf, ctypes.c_void_p)))

    def recv(self):
        buf = ctypes.create_string_buffer(self.elem_size)
        ok = self._l.pt_channel_recv(self._h, ctypes.cast(buf, ctypes.c_void_p))
        return buf.raw if ok else None

    def close(self):
        self._l.pt_channel_close(self._h)

    def __len__(self):
        return self._l.pt_channel_size(self._h)

    @property
    def closed(self) -> bool:
        return bool(self._l.pt_channel_is_closed(self._h))

    def __del__(self):
        if getattr(self, "_h", None):
            self._l.pt_channel_destroy(self._h)
            self._h = None


class ThreadPool:
    """Native worker pool (reference framework/threadpool.h)."""

    def __init__(self, num_threads: int = 0):
        self._l = lib()
        self._h = self._l.pt_threadpool_create(num_threads)
        self._keepalive = []

    @property
    def num_threads(self) -> int:
        return self._l.pt_threadpool_num_threads(self._h)

    def submit(self, fn):
        """Run zero-arg python callable on a pool thread."""
        cb_holder = []

        def trampoline(_):
            try:
                fn()
            finally:
                self._keepalive.remove(cb_holder[0])

        cb = TASK_FN(trampoline)
        cb_holder.append(cb)
        self._keepalive.append(cb)
        self._l.pt_threadpool_submit(
            self._h, ctypes.cast(cb, ctypes.c_void_p), None
        )

    def wait(self):
        self._l.pt_threadpool_wait(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._l.pt_threadpool_destroy(self._h)
            self._h = None


class NativeLoader:
    """Shuffle/batch/prefetch input pipeline running on native threads.

    The TPU-native double_buffer reader (reference framework/reader.h
    decorators, PyDataProvider2's async pool): Python pushes fixed-shape
    samples (the ctypes call releases the GIL for the copy), a native worker
    shuffles with a seeded RNG, stacks samples into contiguous per-slot batch
    buffers from a buddy-allocated arena, and double-buffers ready batches
    (`prefetch_depth`) so host assembly overlaps device compute.

    slots: list of (shape, dtype) per sample component, e.g.
           [((3, 32, 32), np.float32), ((1,), np.int32)].
    """

    def __init__(self, slots, batch_size, shuffle_buf=0, seed=0,
                 prefetch_depth=2, drop_last=False):
        import numpy as np

        self._l = lib()
        self.slots = [
            (tuple(shape), np.dtype(dt)) for shape, dt in slots
        ]
        self.batch_size = batch_size
        nbytes = [
            int(np.prod(shape)) * dt.itemsize for shape, dt in self.slots
        ]
        arr = (ctypes.c_size_t * len(nbytes))(*nbytes)
        self._h = self._l.pt_loader_create(
            len(nbytes), arr, batch_size, shuffle_buf, seed, prefetch_depth,
            1 if drop_last else 0,
        )

    def push(self, *arrays) -> bool:
        """Push one sample (one contiguous array per slot)."""
        import numpy as np

        if len(arrays) != len(self.slots):
            raise ValueError(
                f"expected {len(self.slots)} slots, got {len(arrays)}"
            )
        ptrs = (ctypes.c_void_p * len(arrays))()
        keep = []
        for i, (a, (shape, dt)) in enumerate(zip(arrays, self.slots)):
            a = np.ascontiguousarray(a, dtype=dt)
            if a.shape != shape:
                raise ValueError(
                    f"slot {i}: expected shape {shape}, got {a.shape}"
                )
            keep.append(a)
            ptrs[i] = a.ctypes.data
        return bool(self._l.pt_loader_push(self._h, ptrs))

    def finish_epoch(self):
        self._l.pt_loader_finish_epoch(self._h)

    def next_batch(self):
        """Blocking: next batch as a tuple of numpy arrays, or None at epoch
        end.  The arrays are copies owned by Python (safe to hold)."""
        import numpy as np

        b = self._l.pt_loader_next(self._h)
        if not b:
            return None
        n = self._l.pt_batch_n(b)
        out = []
        for i, (shape, dt) in enumerate(self.slots):
            addr = self._l.pt_batch_slot(b, i)
            nbytes = n * int(np.prod(shape)) * dt.itemsize
            buf = (ctypes.c_char * nbytes).from_address(addr)
            out.append(
                np.frombuffer(buf, dtype=dt).reshape((n,) + shape).copy()
            )
        self._l.pt_batch_release(self._h, b)
        return tuple(out)

    def run(self, sample_reader):
        """Feed `sample_reader` (yields per-slot tuples) on a background
        Python thread; yield assembled batches until the epoch drains."""
        import threading

        def feed():
            for sample in sample_reader():
                if not isinstance(sample, (tuple, list)):
                    sample = (sample,)
                if not self.push(*sample):
                    return  # loader shut down
            self.finish_epoch()

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        while True:
            b = self.next_batch()
            if b is None:
                break
            yield b
        t.join()

    def __del__(self):
        if getattr(self, "_h", None):
            self._l.pt_loader_destroy(self._h)
            self._h = None


class BuddyAllocator:
    """Buddy-system host allocator (reference memory/detail/buddy_allocator.h).

    alloc() returns raw addresses (ints) inside native arena chunks; use
    with `view()` to get zero-copy numpy arrays over allocator memory.
    """

    def __init__(self, min_block_log2: int = 6, chunk_log2: int = 26):
        self._l = lib()
        self._h = self._l.pt_buddy_create(min_block_log2, chunk_log2)

    def alloc(self, nbytes: int) -> int:
        p = self._l.pt_buddy_alloc(self._h, nbytes)
        if not p:
            raise MemoryError(f"buddy allocator failed for {nbytes} bytes")
        return p

    def free(self, addr: int):
        self._l.pt_buddy_free(self._h, addr)

    def view(self, addr: int, shape, dtype):
        import numpy as np

        dtype = np.dtype(dtype)
        n = int(np.prod(shape)) * dtype.itemsize
        buf = (ctypes.c_char * n).from_address(addr)
        return np.frombuffer(buf, dtype=dtype).reshape(shape)

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 4)()
        self._l.pt_buddy_stats(self._h, out)
        return {
            "arena_bytes": out[0],
            "in_use": out[1],
            "peak_in_use": out[2],
            "num_chunks": out[3],
        }

    def __del__(self):
        if getattr(self, "_h", None):
            self._l.pt_buddy_destroy(self._h)
            self._h = None
