"""Reader composition toolkit: a reader is a zero-arg callable returning an
iterable of samples.

Reference: /root/reference/python/paddle/v2/reader/decorator.py:29-296
(map_readers, shuffle, chain, compose, buffered, firstn, xmap_readers) and
minibatch.py (batch).
"""
from __future__ import annotations

import itertools
import queue
import random
import threading

__all__ = [
    "map_readers",
    "buffered",
    "compose",
    "chain",
    "shuffle",
    "firstn",
    "xmap_readers",
    "cache",
    "batch",
    "bucket_by_length",
    "native_pipeline",
    "prefetch_feeder",
    "PrefetchIterator",
    "PrefetchReader",
    "stage_to_device",
    "PipeReader",
    "ComposeNotAligned",
]


from . import creator  # noqa: E402,F401


class ComposeNotAligned(ValueError):
    pass


class _Error:
    """Exception carrier for worker->consumer queues: background reader
    failures re-raise in the consumer instead of truncating the stream."""

    def __init__(self, exc):
        self.exc = exc


def map_readers(func, *readers):
    """Reader applying `func` across the outputs of several readers
    (decorator.py map_readers)."""

    def reader():
        rs = [r() for r in readers]
        for args in zip(*rs):
            yield func(*args)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle (decorator.py shuffle)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers (decorator.py chain)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples (decorator.py compose)."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Prefetch into a bounded buffer on a worker thread
    (decorator.py buffered) — a host-side PrefetchIterator (no feed
    packing, no device transfer), which also gives abandoned streams a
    clean worker shutdown instead of a thread blocked on a full queue.
    `size <= 0` means unbounded, as before.  The generator wrapper keeps
    the original laziness: nothing is consumed from the source until the
    first next() (side-effecting sources like cloud_reader must not
    drain tasks at construction time)."""

    def data_reader():
        from .pipeline import PrefetchIterator

        it = PrefetchIterator(reader, feeder=None, device_put=False,
                              depth=size if size > 0 else 2 ** 30)
        try:
            yield from it
        finally:
            it.close()

    return data_reader


def firstn(reader, n):
    def data_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads
    (decorator.py xmap_readers).  `order=True` preserves input order."""

    class _End:
        pass

    def data_reader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d))
                for _ in range(process_num):
                    in_q.put(_End)
            except BaseException as e:
                # wake every worker with the error so each forwards one
                # _Error/_End downstream and the consumer can't deadlock
                for _ in range(process_num):
                    in_q.put(_Error(e))

        def work():
            item = in_q.get()
            try:
                while item is not _End:
                    if isinstance(item, _Error):
                        out_q.put(item)
                        return
                    i, d = item
                    out_q.put((i, mapper(d)))
                    item = in_q.get()
                out_q.put(_End)
            except BaseException as e:  # mapper raised
                out_q.put(_Error(e))

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        if order:
            pending = {}
            want = 0
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                if isinstance(item, _Error):
                    raise item.exc
                i, d = item
                pending[i] = d
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                if isinstance(item, _Error):
                    raise item.exc
                yield item[1]

    return data_reader


def cache(reader):
    """Materialize once, replay from memory."""
    all_data = []
    filled = []

    def data_reader():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)

    return data_reader


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (reference minibatch.py)."""

    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


def bucket_by_length(reader, batch_size, boundaries, seq_slots=(0,),
                     key_slot=None, pad_value=0, drop_last=False):
    """Bucketed batching for variable-length samples: bounds XLA
    executable count to len(boundaries) per program with `drop_last`
    (up to 2*len(boundaries) without it — each bucket's final partial
    batch adds at most one extra shape).

    The LoD offset table is part of the compile-cache key (core/lod.py), so
    feeding raw per-batch length multisets recompiles per batch — the TPU
    answer to the reference's zero-recompile dynamic batching
    (lod_rank_table_op.cc / while_op.cc dynamic shapes) is static length
    buckets.  Samples are pooled by the bucket of ``len(sample[key_slot])``
    (default: the first seq slot); when a pool reaches `batch_size` a batch
    is yielded in which every slot in `seq_slots` is right-padded with
    `pad_value` to the bucket boundary, so every batch from a bucket has
    the SAME shapes + LoD and hits the same executable.

    Sequences longer than the last boundary are truncated to it.  Padding
    rows are real rows at the LoD level — models that must ignore them
    should mask (or choose a benign pad token, e.g. an embedding id whose
    vector is zero).  Partial pools are flushed at exhaustion unless
    `drop_last` (each flush costs at most one extra compile per bucket).
    """
    bounds = sorted({int(b) for b in boundaries})
    if not bounds:
        raise ValueError("boundaries must be non-empty")
    key = seq_slots[0] if key_slot is None else key_slot

    def bucket_of(n):
        for b in bounds:
            if n <= b:
                return b
        return bounds[-1]

    def pad(sample, bound):
        row = list(sample)
        for s in seq_slots:
            seq = list(row[s])[:bound]
            fill = bound - len(seq)
            if fill:
                seq = seq + [pad_value] * fill
            row[s] = seq
        return tuple(row)

    def bucket_reader():
        pools = {b: [] for b in bounds}
        for sample in reader():
            b = bucket_of(len(sample[key]))
            pool = pools[b]
            pool.append(pad(sample, b))
            if len(pool) == batch_size:
                yield pool[:]
                pool.clear()
        if not drop_last:
            for b in bounds:
                if pools[b]:
                    yield pools[b]

    return bucket_reader


def native_pipeline(reader, slots, batch_size, shuffle_buf=0, seed=0,
                    prefetch_depth=2, drop_last=False):
    """Fused shuffle+batch+double_buffer on native threads: yields tuples of
    stacked numpy arrays, one per slot.

    The native replacement for `shuffle(...) |> batch(...) |> buffered(...)`
    when samples are fixed-shape: shuffling, the stacking memcpy and prefetch
    all run off the GIL in C++ (paddle_tpu/native/src/loader.cc), overlapping
    the input pipeline with device compute — the role the reference's
    double_buffer reader (framework/reader.h) and PyDataProvider2's async
    pool play.

    slots: [(shape, dtype), ...] of one sample's components.
    """
    from paddle_tpu.native import NativeLoader

    loader = NativeLoader(slots, batch_size, shuffle_buf=shuffle_buf,
                          seed=seed, prefetch_depth=prefetch_depth,
                          drop_last=drop_last)

    def batch_reader():
        return loader.run(reader)

    batch_reader.loader = loader
    return batch_reader


class PipeReader:
    """Stream records from a shell command's stdout (reference
    decorator.py:337 PipeReader) — `cat file`, `curl url`,
    `hadoop fs -cat ...`; file_type="gzip" decompresses inline."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        import codecs
        import shlex
        import subprocess
        import zlib

        if not isinstance(command, str):
            raise TypeError("command must be a string")
        if file_type not in ("plain", "gzip"):
            raise TypeError(f"file_type must be plain/gzip, got {file_type}")
        self.file_type = file_type
        if file_type == "gzip":
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
        self.bufsize = bufsize
        # incremental decoder: a multibyte char split across read chunks
        # must not raise
        self._decoder = codecs.getincrementaldecoder("utf-8")()
        self.process = subprocess.Popen(
            shlex.split(command), bufsize=bufsize, stdout=subprocess.PIPE)

    def close(self):
        """Terminate the child (early-stopping consumers must call this,
        or the child blocks forever on a full pipe)."""
        if self.process.poll() is None:
            self.process.terminate()
        self.process.wait()

    def _gunzip(self, buff):
        """Decompress, restarting the decompressor at gzip member
        boundaries — concatenated .gz parts (`cat a.gz b.gz`) must not
        silently truncate after the first member."""
        import zlib

        out = b""
        while buff:
            out += self.dec.decompress(buff)
            if not self.dec.eof:
                break
            buff = self.dec.unused_data
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
        return out

    def get_line(self, cut_lines=True, line_break="\n"):
        remained = ""
        while True:
            buff = self.process.stdout.read(self.bufsize)
            if not buff:
                break
            if self.file_type == "gzip":
                buff = self._gunzip(buff)
            decomp_buff = self._decoder.decode(buff)
            if cut_lines:
                lines = (remained + decomp_buff).split(line_break)
                remained = lines.pop(-1)
                yield from lines
            else:
                yield decomp_buff
        remained += self._decoder.decode(b"", final=True)
        if remained:
            yield remained
        rc = self.process.wait()
        if rc != 0:
            raise RuntimeError(f"PipeReader command failed with exit {rc}")


# imported last: pipeline reuses this module's _Error carrier
from .pipeline import (  # noqa: E402,F401
    PrefetchIterator,
    PrefetchReader,
    prefetch_feeder,
    stage_to_device,
)
