"""Async input pipeline: overlap reader -> feed-pack -> H2D with compute.

The reference overlaps input preparation with compute through the async
PyDataProvider2 pool and the gserver double-buffered data providers
(framework/reader.h double_buffer); our Trainer loop was fully serial —
`DataFeeder.feed` packed numpy on the host while the device idled.  On
TPU, dispatch is async by design, so the whole host-side portion of a
step is hideable: this module runs the batch reader, the feed packing
and an eager `jax.device_put` on a background thread ahead of the
training loop, handing the consumer feed dicts whose values are already
device-resident.

Layering: this sits ON TOP of the reader decorators (shuffle/batch/
bucket_by_length/...), not instead of them — `prefetch_feeder(reader,
feeder)` takes any batch reader and returns another zero-arg reader
(the package idiom), whose iterator is a `PrefetchIterator` with clean
shutdown (`close()`), bounded-queue backpressure, and exception
propagation (a reader/feeder failure re-raises in the consumer instead
of truncating the stream, same contract as `buffered`).

The H2D staging stage (`stage_to_device`) is shared with the serving
worker's batch assembly (serving.py), so both hot paths emit the same
`pipeline.h2d` profiler events.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time

__all__ = ["prefetch_feeder", "PrefetchIterator", "PrefetchReader",
           "stage_to_device"]

from . import _Error
from ..observability import attribution as obs_attr
from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing

# pipeline telemetry (gated by PADDLE_TPU_METRICS): queue occupancy
# answers "is the reader keeping up" (pinned near `depth` = yes, near 0
# with high wait = the reader is the bottleneck; docs/performance.md).
# The gauge is labeled per iterator — concurrent streams must not
# clobber one series — and close() reclaims it, so a finished stream
# does not export a stale depth forever.
_PIPE_IDS = itertools.count()
_M_QUEUE_DEPTH = obs_metrics.gauge(
    "paddle_tpu_pipeline_queue_depth",
    "prefetch queue occupancy (packed device-resident batches ready)",
    ("pipe",))
_M_WAIT_SECONDS = obs_metrics.histogram(
    "paddle_tpu_pipeline_wait_seconds",
    "consumer blocked on an empty prefetch queue per batch")


class _End:
    pass


def stage_to_device(value, device):
    """H2D-stage one feed value (LoDTensor wrappers preserved), emitting a
    `pipeline.h2d` profiler event — the single staging stage shared by the
    training prefetch pipeline and the serving worker's batch assembly."""
    from paddle_tpu import profiler
    from paddle_tpu.core.executor import _to_device_value

    with profiler.record_event("pipeline.h2d"):
        return _to_device_value(value, device)


class PrefetchIterator:
    """One epoch of prefetched feeds: a daemon thread runs
    `reader() -> feeder.feed -> device_put` into a bounded queue.

    * backpressure: the queue holds at most `depth` packed batches, so a
      slow consumer bounds host memory and the worker's readahead;
    * errors: any exception in the reader/feeder/transfer re-raises at the
      consumer's next `__next__` (after already-queued good batches);
    * shutdown: `close()` (idempotent; also called on exhaustion) stops
      the worker and joins it, so breaking out of a pass early never
      leaks a thread blocked on a full queue.  NOTE: a live worker holds
      a reference to this iterator (the thread's bound-method target),
      so an ABANDONED iterator is not garbage-collected — consumers that
      may abandon mid-stream should hold the `PrefetchReader` wrapper
      (what `prefetch_feeder` returns), whose `__del__` IS reachable and
      closes the inner iterator.
    """

    def __init__(self, reader, feeder=None, place=None, depth=2,
                 device_put=True):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        # cumulative consumer-side blocked time (queue empty): the
        # host-blocked numerator a bench can read without enabling the
        # profiler (whose compiled-mode events fence the device)
        self.wait_s = 0.0
        self._feeder = feeder
        self._device_put = device_put
        # thread handoff: batches prepared on the worker record under
        # the span that constructed the iterator (e.g. trainer.step /
        # the pass that opened the reader)
        self._trace_ctx = obs_tracing.current_context()
        self._pipe_id = str(next(_PIPE_IDS))
        self._m_depth = _M_QUEUE_DEPTH.labels(pipe=self._pipe_id)
        place = place or getattr(feeder, "place", None)
        self._device = place.jax_device() if place is not None else None
        if device_put and self._device is None:
            import jax

            self._device = jax.devices()[0]
        self.thread = threading.Thread(
            target=self._work, args=(reader,), daemon=True,
            name="paddle-tpu-prefetch")
        self.thread.start()

    # -- worker -------------------------------------------------------------
    def _put(self, item) -> bool:
        """Blocking put that wakes up when the consumer closes early."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _prepare(self, batch):
        if self._feeder is not None:
            with obs_attr.phase("trainer", "feed_pack"):
                feed = self._feeder.feed(batch)
        else:
            feed = batch  # reader already yields feed dicts
        if not self._device_put:
            return feed
        with obs_attr.phase("trainer", "h2d"):
            if isinstance(feed, dict):
                feed = {k: stage_to_device(v, self._device)
                        for k, v in feed.items()}
            else:
                feed = stage_to_device(feed, self._device)
        return feed

    def _work(self, reader):
        try:
            with obs_tracing.activate(self._trace_ctx):
                for batch in reader():
                    if self._stop.is_set():
                        return
                    with obs_tracing.span("pipeline.prepare"):
                        item = self._prepare(batch)
                    if not self._put(item):
                        return
                    if obs_metrics.enabled():
                        self._m_depth.set(self._q.qsize())
                self._put(_End)
        except BaseException as e:  # propagate, don't truncate the stream
            self._put(_Error(e))

    # -- consumer -----------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        from paddle_tpu import profiler

        if self._done:
            raise StopIteration
        with profiler.record_event("pipeline.wait"):
            t0 = time.perf_counter()
            item = self._q.get()
            dt = time.perf_counter() - t0
            self.wait_s += dt
        if obs_metrics.enabled():
            _M_WAIT_SECONDS.observe(dt)
            self._m_depth.set(self._q.qsize())
        if item is _End:
            self._done = True
            self.thread.join(timeout=5)
            raise StopIteration
        if isinstance(item, _Error):
            self._done = True
            self._stop.set()
            raise item.exc
        return item

    def close(self):
        """Stop the worker and join it (safe to call more than once)."""
        self._done = True
        self._stop.set()
        _M_QUEUE_DEPTH.remove(pipe=self._pipe_id)
        while True:  # drain so a blocked put wakes immediately
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self.thread.is_alive():
            self.thread.join(timeout=5)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PrefetchReader:
    """Lazy one-epoch handle: the PrefetchIterator (and its worker
    thread) starts at the FIRST `next()`, not at construction — the
    package reader contract (`compose`/`zip` call every reader before
    consuming any; side-effecting sources like `cloud_reader` must not
    drain tasks for a stream nobody iterates).  Because the worker only
    references the INNER iterator, dropping this handle is collectable:
    `__del__` closes the iterator, so an abandoned stream (early `break`
    without `close()`) leaks neither the thread nor the queued
    device-resident batches."""

    def __init__(self, reader, feeder=None, place=None, depth=2,
                 device_put=True):
        self._args = (reader, feeder, place, depth, device_put)
        self._it: "PrefetchIterator | None" = None
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        if self._it is None:
            reader, feeder, place, depth, device_put = self._args
            self._it = PrefetchIterator(reader, feeder=feeder,
                                        place=place, depth=depth,
                                        device_put=device_put)
        return next(self._it)

    @property
    def wait_s(self) -> float:
        """Consumer-side blocked seconds (see PrefetchIterator.wait_s)."""
        return self._it.wait_s if self._it is not None else 0.0

    def close(self):
        self._closed = True
        if self._it is not None:
            self._it.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def prefetch_feeder(reader, feeder=None, place=None, depth=2,
                    device_put=True):
    """Reader decorator: batch reader -> reader of DEVICE-RESIDENT feed
    dicts, prepared `depth` batches ahead on a background thread.

        feeds = prefetch_feeder(train_reader, feeder, place, depth=2)
        for feed in feeds():
            exe.run(main, feed=feed, fetch_list=[loss])

    `feeder=None` means the reader already yields feed dicts and only the
    device transfer is staged; `device_put=False` keeps values on host
    (pure pack-ahead).  Each call of the returned reader yields a fresh
    `PrefetchReader` (own thread + queue once iterated), so it composes
    with the multi-pass Trainer loop exactly like any other reader.
    """

    def feed_reader():
        return PrefetchReader(reader, feeder=feeder, place=place,
                              depth=depth, device_put=device_put)

    return feed_reader
