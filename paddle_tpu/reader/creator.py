"""Reader creators (reference python/paddle/v2/reader/creator.py):
np_array, text_file, recordio, cloud_reader.

`recordio` here is a minimal self-contained chunked record format
(length-prefixed pickled records — the reference links the recordio C
library); `cloud_reader` pulls task chunks from the cloud master
(cloud/master.py — the etcd/master-client analogue, reference
creator.py:91-117).
"""
from __future__ import annotations

import glob
import pickle
import struct

__all__ = ["np_array", "text_file", "recordio", "cloud_reader",
           "write_recordio"]

_LEN = struct.Struct("<I")


def np_array(x):
    """Yield rows of a numpy array (reference creator.py:22)."""

    def reader():
        for row in x:
            yield row

    return reader


def text_file(path):
    """Yield stripped lines of a text file (reference creator.py:42)."""

    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def write_recordio(path, records) -> int:
    """Write pickled records length-prefixed; returns the record count
    (writer half of the recordio analogue)."""
    n = 0
    with open(path, "wb") as f:
        for r in records:
            payload = pickle.dumps(r)
            f.write(_LEN.pack(len(payload)))
            f.write(payload)
            n += 1
    return n


def _read_recordio_file(path):
    with open(path, "rb") as f:
        while True:
            head = f.read(_LEN.size)
            if len(head) < _LEN.size:
                return
            (n,) = _LEN.unpack(head)
            yield pickle.loads(f.read(n))


def _expand_paths(paths):
    """Comma-separated string or list -> concrete file list (glob
    patterns expanded; non-matching entries kept verbatim)."""
    if isinstance(paths, str):
        paths = paths.split(",")
    files = []
    for p in paths:
        files.extend(sorted(glob.glob(p)) or [p])
    return files


def recordio(paths, buf_size=100):
    """Reader over recordio file paths — comma-separated string, glob
    patterns supported (reference creator.py:60)."""
    from . import buffered

    files = _expand_paths(paths)

    def reader():
        for path in files:
            yield from _read_recordio_file(path)

    return buffered(reader, buf_size)


def cloud_reader(paths, master_endpoint, timeout_sec=5, buf_size=64):
    """Elastic cloud reader: the master shards the file list into tasks
    and hands them to trainers; any trainer may die/join (reference
    creator.py:91 cloud_reader over etcd; here the transport is the
    native master service, cloud/master.py)."""
    from ..cloud.master import MasterClient, task_record_reader
    from . import buffered

    files = _expand_paths(paths)
    client = MasterClient(master_endpoint, timeout=timeout_sec)
    client.set_dataset(files)

    def chunk_reader(chunk_path):
        yield from _read_recordio_file(chunk_path)

    reader = buffered(task_record_reader(client, chunk_reader), buf_size)
    # exposed so callers can release the connection (a live client blocks
    # a graceful master shutdown)
    reader.master_client = client
    return reader
