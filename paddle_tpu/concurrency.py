"""CSP concurrency surface: make_channel / channel_send / channel_recv /
channel_close / go.

Reference: doc/design/csp.md + framework/channel.h (the C++ Go-style
channels) and the *aspirational* Python surface in
tests/notest_csp.py:19-33 — the reference's DSL never implemented
`fluid.make_channel/go/send/recv` (SURVEY.md §2.1 "Channels").  Here the
surface actually works: channels are the native C++ buffered/unbuffered
channels (native/src/channel.cc) carrying pickled Python values, and
`go()` runs its block on the native thread pool.

This is host-side orchestration (reader pipelines, daisy-chained
producers, actor-ish plumbing) — not traced program state; device compute
launched inside a goroutine goes through the normal executor.
"""
from __future__ import annotations

import contextlib
import pickle
import struct
import threading

from . import native

__all__ = ["make_channel", "channel_send", "channel_recv", "channel_close",
           "go", "Go"]

_PTR = struct.Struct("<Q")


class _PyChannel:
    """Typed channel of Python objects over a native bytes channel.

    The native channel moves fixed-size elements; we move an 8-byte index
    into a side table holding the pickled payloads (keeps arbitrary-size
    objects while the blocking/closing semantics stay native)."""

    def __init__(self, dtype=None, capacity: int = 0):
        self.dtype = dtype
        self._ch = native.Channel(elem_size=_PTR.size, capacity=capacity)
        self._table = {}
        self._next = 0
        self._mu = threading.Lock()

    def send(self, value) -> bool:
        if self.dtype is not None and value is not None \
                and not isinstance(value, self.dtype):
            raise TypeError(
                f"channel of {self.dtype.__name__} got "
                f"{type(value).__name__}")
        with self._mu:
            idx = self._next
            self._next += 1
            self._table[idx] = pickle.dumps(value)
        ok = self._ch.send(_PTR.pack(idx))
        if not ok:
            with self._mu:
                self._table.pop(idx, None)
        return ok

    def recv(self):
        raw = self._ch.recv()
        if raw is None:
            return None  # closed and drained (Go zero-value convention)
        (idx,) = _PTR.unpack(raw)
        with self._mu:
            payload = self._table.pop(idx)
        return pickle.loads(payload)

    def close(self):
        self._ch.close()

    def __len__(self):
        return len(self._ch)


def make_channel(dtype=None, capacity: int = 0) -> _PyChannel:
    """Unbuffered (capacity=0, rendezvous) or buffered channel
    (reference MakeChannel, channel.h:42)."""
    return _PyChannel(dtype, capacity)


def channel_send(channel: _PyChannel, value) -> bool:
    """Blocking send; False if the channel closed (channel.h Send)."""
    return channel.send(value)


def channel_recv(channel: _PyChannel):
    """Blocking recv; None once closed and drained (channel.h Receive)."""
    return channel.recv()


def channel_close(channel: _PyChannel):
    channel.close()


class Go:
    """`with go():` runs the block body in a goroutine-style task.

    The body executes asynchronously on a daemon thread; exceptions are
    re-raised on `wait()` (the reference design doc's go-op semantics,
    doc/design/csp.md)."""

    def __init__(self):
        self._thread = None
        self._exc = None

    def _run(self, fn):
        try:
            fn()
        except BaseException as e:  # surfaced on wait()
            self._exc = e

    def spawn(self, fn):
        self._thread = threading.Thread(target=self._run, args=(fn,),
                                        daemon=True)
        self._thread.start()
        return self

    def wait(self, timeout=None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("goroutine still running after timeout")
        if self._exc is not None:
            raise self._exc


@contextlib.contextmanager
def go():
    """Collect the block body and run it asynchronously.

    Python `with` blocks can't defer their own body, so the block
    registers callables:

        with fluid.go() as g:
            g(lambda: fluid.channel_send(ch, compute()))

    Every registered callable runs concurrently; `g.wait()` joins."""
    tasks = []

    class _Spawner:
        _handles = None  # set when the with-block exits

        def __call__(self, fn):
            tasks.append(fn)
            return fn

        def wait(self, timeout=None):
            if self._handles is None:
                raise RuntimeError(
                    "g.wait() called inside the `with go()` block — tasks "
                    "only spawn when the block exits")
            for h in self._handles:
                h.wait(timeout)

    sp = _Spawner()
    yield sp
    sp._handles = [Go().spawn(fn) for fn in tasks]
