"""`python -m paddle_tpu.cli` — the legacy trainer command line.

Reference: /root/reference/paddle/trainer/TrainerMain.cpp:24-60 (`paddle
train --config=... --job=train|test|checkgrad|time`, plus ParamUtil save
dirs / --start_pass resume) and paddle/scripts (`paddle train` wrapper).
The `merge` job is the MergeModel utility (trainer/MergeModel.cpp): fold
config + trained parameters into one deployable inference file.

Config contract (the config_parser.py analogue — a plain Python file):

    # config.py
    import paddle_tpu as fluid

    def build():
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        def reader():          # yields feed dicts
            while True:
                yield {"x": ..., "y": ...}
        return {
            "loss": loss,                         # required
            "reader": reader,                     # required for train/test/time
            "optimizer": fluid.SGD(0.01),         # default SGD(0.01)
            "test_reader": reader,                # default: reader
            "infer_targets": [pred],              # required for --job=merge
            "feed_order": ["x", "y"],             # optional (dict feeds don't need it)
        }

`build()` is called inside a fresh `program_guard`, so the config only
describes the network — program bookkeeping is the CLI's job.
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import sys
import time

import numpy as np


def _load_config(path):
    spec = importlib.util.spec_from_file_location("paddle_cli_config",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    if not hasattr(mod, "build"):
        raise SystemExit(f"config {path!r} must define build()")
    return mod


def _build(mod):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        cfg = mod.build()
    if "loss" not in cfg:
        raise SystemExit("build() must return a dict with 'loss'")
    cfg["main"], cfg["startup"] = main, startup
    return cfg


def _place(use_tpu):
    import paddle_tpu as fluid

    return fluid.TPUPlace() if use_tpu else fluid.CPUPlace()


def _run_startup_or_load(exe, cfg, args):
    import paddle_tpu as fluid

    exe.run(cfg["startup"])
    if args.init_model_path:
        fluid.io.load_persistables(exe, args.init_model_path,
                                   main_program=cfg["main"])


def job_train(cfg, args):
    import paddle_tpu as fluid

    loss = cfg["loss"]
    opt = cfg.get("optimizer") or fluid.SGD(learning_rate=0.01)
    with fluid.program_guard(cfg["main"], cfg["startup"]):
        opt.minimize(loss)
    exe = fluid.Executor(_place(args.use_tpu))
    _run_startup_or_load(exe, cfg, args)
    reader = cfg["reader"]
    for pass_id in range(args.num_passes):
        costs = []
        for batch_id, feed in enumerate(reader()):
            if args.batches_per_pass and batch_id >= args.batches_per_pass:
                break
            out, = exe.run(cfg["main"], feed=feed, fetch_list=[loss])
            costs.append(float(np.asarray(out).reshape(-1)[0]))
            if args.log_period and batch_id % args.log_period == 0:
                print(f"pass {pass_id} batch {batch_id} "
                      f"cost {costs[-1]:.6f}")
        print(f"pass {pass_id} done, avg cost "
              f"{np.mean(costs) if costs else float('nan'):.6f}")
        if args.save_dir:
            d = os.path.join(args.save_dir, f"pass-{pass_id:05d}")
            os.makedirs(d, exist_ok=True)
            fluid.io.save_persistables(exe, d, main_program=cfg["main"])
            print(f"saved parameters to {d}")


def job_test(cfg, args):
    import paddle_tpu as fluid

    if not args.init_model_path:
        raise SystemExit(
            "--job=test requires --init_model_path (otherwise it would "
            "evaluate freshly initialized random parameters)")
    loss = cfg["loss"]
    test_prog = cfg["main"].clone(for_test=True)
    exe = fluid.Executor(_place(args.use_tpu))
    _run_startup_or_load(exe, cfg, args)
    reader = cfg.get("test_reader") or cfg["reader"]
    costs = []
    for batch_id, feed in enumerate(reader()):
        if args.batches_per_pass and batch_id >= args.batches_per_pass:
            break
        out, = exe.run(test_prog, feed=feed, fetch_list=[loss])
        costs.append(float(np.asarray(out).reshape(-1)[0]))
    print(f"test: {len(costs)} batches, avg cost {np.mean(costs):.6f}")


def job_time(cfg, args):
    """`--job=time` (reference benchmark mode: paddle train --job=time,
    benchmark/paddle/image/run.sh)."""
    import paddle_tpu as fluid

    loss = cfg["loss"]
    opt = cfg.get("optimizer") or fluid.SGD(learning_rate=0.01)
    with fluid.program_guard(cfg["main"], cfg["startup"]):
        opt.minimize(loss)
    exe = fluid.Executor(_place(args.use_tpu))
    _run_startup_or_load(exe, cfg, args)
    it = cfg["reader"]()
    feed = next(iter(it))
    exe.run(cfg["main"], feed=feed, fetch_list=[loss])   # compile+warmup
    n = args.batches_per_pass or 10
    t0 = time.perf_counter()
    for _ in range(n):
        out, = exe.run(cfg["main"], feed=feed, fetch_list=[loss])
    np.asarray(out)
    ms = (time.perf_counter() - t0) / n * 1000
    print(f"time: {ms:.2f} ms/batch over {n} batches")


def job_checkgrad(cfg, args):
    """Central finite-difference check of d(loss)/d(param) (reference
    --job=checkgrad, trainer/tests + gserver test_LayerGrad machinery)."""
    import paddle_tpu as fluid
    from paddle_tpu.core.executor import global_scope

    loss = cfg["loss"]
    main = cfg["main"]
    params = main.global_block().all_parameters()
    with fluid.program_guard(main, cfg["startup"]):
        grads = fluid.calc_gradient(loss, params)
    exe = fluid.Executor(_place(args.use_tpu))
    _run_startup_or_load(exe, cfg, args)
    feed = next(iter(cfg["reader"]()))
    scope = global_scope()
    fetched = exe.run(main, feed=feed, fetch_list=[loss] + list(grads))
    analytic = {p.name: np.asarray(g) for p, g in zip(params, fetched[1:])}

    delta = args.checkgrad_eps
    rng = np.random.RandomState(0)
    worst = 0.0
    for p in params:
        val = np.asarray(scope.find_var(p.name)).copy()
        flat = val.reshape(-1)
        k = min(args.checkgrad_samples, flat.size)
        idxs = rng.choice(flat.size, size=k, replace=False)
        num = np.zeros(k)
        for j, i in enumerate(idxs):
            for sgn in (+1, -1):
                flat2 = flat.copy()
                flat2[i] += sgn * delta
                scope.set_var(p.name, flat2.reshape(val.shape))
                out, = exe.run(main, feed=feed, fetch_list=[loss])
                num[j] += sgn * float(np.asarray(out).reshape(-1)[0])
            num[j] /= 2 * delta
        scope.set_var(p.name, val)
        ana = analytic[p.name].reshape(-1)[idxs]
        denom = np.maximum(np.abs(num) + np.abs(ana), 1e-6)
        err = float(np.max(np.abs(num - ana) / denom))
        worst = max(worst, err)
        status = "OK" if err < args.checkgrad_tol else "FAIL"
        print(f"checkgrad {p.name}: max rel err {err:.3e} [{status}]")
    if worst >= args.checkgrad_tol:
        raise SystemExit(f"checkgrad FAILED (worst {worst:.3e} >= "
                         f"{args.checkgrad_tol})")
    print(f"checkgrad passed (worst {worst:.3e})")


def job_merge(cfg, args):
    """MergeModel: config + params -> single-file inference model."""
    import paddle_tpu as fluid

    targets = cfg.get("infer_targets")
    if not targets:
        raise SystemExit("--job=merge needs 'infer_targets' from build()")
    if not args.init_model_path:
        raise SystemExit(
            "--job=merge requires --init_model_path (otherwise it would "
            "package freshly initialized random parameters)")
    exe = fluid.Executor(_place(args.use_tpu))
    _run_startup_or_load(exe, cfg, args)
    feed_names = cfg.get("feed_order")
    if not feed_names:
        raise SystemExit("--job=merge needs 'feed_order' from build()")
    out = args.save_dir or "merged_model"
    fluid.io.save_inference_model(
        out, feed_names, targets, exe, main_program=cfg["main"],
        model_filename="__model__", params_filename="__params__")
    print(f"merged model written to {out}")


# ---------------------------------------------------------------------------
# `metrics` / `trace` subcommands: observability surface (docs/
# observability.md)
# ---------------------------------------------------------------------------


def _snapshot_scalars(snap):
    """{(name, label-items) -> (type, value)} for counters/gauges plus
    histogram _count/_sum pseudo-series — the diffable subset of a
    JSON snapshot."""
    out = {}
    for name, m in snap.get("metrics", {}).items():
        for s in m["samples"]:
            key_labels = tuple(sorted(s["labels"].items()))
            if m["type"] == "histogram":
                out[(name + "_count", key_labels)] = (
                    "counter", float(s["value"]["count"]))
                out[(name + "_sum", key_labels)] = (
                    "counter", float(s["value"]["sum"]))
            else:
                out[(name, key_labels)] = (m["type"],
                                           float(s["value"]))
    return out


def _print_metrics_diff(path_a, path_b, snap_a, snap_b):
    """Counter deltas (and gauge before->after) between two snapshots
    — the poor man's rate view over the atexit dumps."""
    from paddle_tpu.observability.exporters import _fmt_labels

    a = _snapshot_scalars(snap_a)
    b = _snapshot_scalars(snap_b)
    dt = float(snap_b.get("time", 0)) - float(snap_a.get("time", 0))
    rows = []
    for key in sorted(set(a) | set(b)):
        name, labels = key
        kind_a, va = a.get(key, (None, 0.0))
        kind_b, vb = b.get(key, (None, 0.0))
        kind = kind_b or kind_a
        label = _fmt_labels(dict(labels))
        if kind == "gauge":
            if va != vb:
                rows.append((f"{name}{label}", "gauge",
                             f"{va:g} -> {vb:g}"))
        else:
            delta = vb - va
            if delta:
                per_s = f"  ({delta / dt:.6g}/s)" if dt > 0 else ""
                rows.append((f"{name}{label}", kind or "counter",
                             f"{delta:+g}{per_s}"))
    print(f"{path_a} -> {path_b}"
          + (f"  (dt {dt:.3f}s)" if dt > 0 else ""))
    if not rows:
        print("no series moved between the two snapshots")
        return
    name_w = max(len(r[0]) for r in rows)
    print(f"{'Metric':<{name_w}}  {'Type':<9}  Delta")
    for n, t, v in rows:
        print(f"{n:<{name_w}}  {t:<9}  {v}")


def cmd_metrics(argv):
    """`python -m paddle_tpu.cli metrics DUMP.json` — render a JSON
    metrics snapshot (observability.exporters.write_json, or the
    --metrics_out of `cli trace`) as a table.  `--diff A.json B.json`
    instead prints the counter deltas (and per-second rates, from the
    snapshots' timestamps) between two dumps."""
    import json

    from paddle_tpu.observability.exporters import format_metrics_table

    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli metrics",
        description="render or diff metrics JSON snapshots")
    ap.add_argument("snapshot", nargs="?", default="",
                    help="JSON snapshot file written by "
                    "observability.exporters.write_json")
    ap.add_argument("--diff", nargs=2, metavar=("A.json", "B.json"),
                    help="print counter deltas between two snapshots "
                    "(A = earlier, B = later)")
    args = ap.parse_args(argv)
    if args.diff:
        path_a, path_b = args.diff
        with open(path_a) as f:
            snap_a = json.load(f)
        with open(path_b) as f:
            snap_b = json.load(f)
        _print_metrics_diff(path_a, path_b, snap_a, snap_b)
        return 0
    if not args.snapshot:
        raise SystemExit("metrics: give a snapshot file or --diff A B")
    with open(args.snapshot) as f:
        snap = json.load(f)
    n = len(snap.get("metrics", {}))
    print(f"{args.snapshot}: {n} metric(s) from pid "
          f"{snap.get('pid', '?')}")
    print(format_metrics_table(snap))
    return 0


def cmd_trace(argv):
    """`python -m paddle_tpu.cli trace CONFIG OUT.json [--steps N]` —
    run a build() config file for a few steps with span recording on and
    write the Chrome-trace JSON (open in chrome://tracing or
    https://ui.perfetto.dev)."""
    import paddle_tpu as fluid
    from paddle_tpu.observability import exporters, metrics, tracing

    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli trace",
        description="run a config under tracing; emit Chrome trace JSON")
    ap.add_argument("config", help="python file defining build() "
                    "(CLI config contract)")
    ap.add_argument("out", help="Chrome-trace JSON output path")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--use_tpu", type=int, default=1)
    ap.add_argument("--metrics_out", default="",
                    help="also write a metrics JSON snapshot here "
                    "(view with `cli metrics`)")
    args = ap.parse_args(argv)

    metrics.set_enabled(True)
    tracing.set_enabled(True)
    mod = _load_config(args.config)
    cfg = _build(mod)
    if "reader" not in cfg:
        raise SystemExit("trace needs 'reader' from build()")
    loss = cfg["loss"]
    opt = cfg.get("optimizer") or fluid.SGD(learning_rate=0.01)
    with fluid.program_guard(cfg["main"], cfg["startup"]):
        opt.minimize(loss)
    exe = fluid.Executor(_place(args.use_tpu))
    exe.run(cfg["startup"])
    it = iter(cfg["reader"]())
    steps = 0
    with tracing.span("cli.trace", config=args.config):
        for i in range(args.steps):
            feed = next(it, None)
            if feed is None:
                break
            with tracing.span("trainer.step", batch_id=i):
                exe.run(cfg["main"], feed=feed, fetch_list=[loss])
            steps += 1
    path = tracing.write_chrome_trace(args.out)
    print(f"trace: {steps} step(s), {len(tracing.finished_spans())} "
          f"span(s) -> {path}")
    if args.metrics_out:
        print(f"metrics snapshot -> "
              f"{exporters.write_json(args.metrics_out)}")
    return 0


# ---------------------------------------------------------------------------
# `top` / `slo` subcommands: the fleet telemetry plane
# (docs/observability.md "Fleet telemetry")
# ---------------------------------------------------------------------------

# which series feed each fleet-table column, per member kind; the
# fallback row renders "-" for kinds without a mapping
_TOP_COLUMNS = {
    "generation": {
        "qps": "paddle_tpu_serving_generation_requests_total",
        "latency": "paddle_tpu_serving_generation_seconds",
        "queue": "paddle_tpu_serving_generation_queue_depth",
        "util": "paddle_tpu_serving_kv_pool_utilization",
    },
    "serving": {
        "qps": "paddle_tpu_serving_requests_total",
        "latency": "paddle_tpu_serving_request_seconds",
        "queue": "paddle_tpu_serving_queue_depth",
    },
    "pserver": {
        "qps": "paddle_tpu_pserver_requests_total",
        "latency": "paddle_tpu_pserver_optimize_seconds",
    },
    "trainer": {
        "qps": "paddle_tpu_trainer_steps_total",
        "latency": "paddle_tpu_trainer_step_seconds",
    },
    "router": {
        "qps": "paddle_tpu_serving_router_requests_total",
        "latency": "paddle_tpu_serving_router_request_seconds",
        "queue": "paddle_tpu_serving_router_outstanding_tokens",
    },
}


def _fmt_stat(v, fmt="{:.3g}"):
    import math

    if v is None or (isinstance(v, float) and math.isnan(v)):
        return "-"
    return fmt.format(v)


def format_fleet_table(coll, window_s: float = 60.0) -> str:
    """The `cli top` table: one row per member with windowed qps /
    p50 / p99 / queue depth / KV utilization from the collector's
    fleet time-series."""
    rows = []
    for m in coll.members():
        cols = _TOP_COLUMNS.get(m["kind"], {})
        lbl = {"member": m["member"]}
        qps = p50 = p99 = queue = util = None
        if "qps" in cols:
            qps = coll.series.rate(cols["qps"], window_s, labels=lbl)
        if "latency" in cols:
            p50 = coll.series.p50(cols["latency"], window_s,
                                  labels=lbl)
            p99 = coll.series.p99(cols["latency"], window_s,
                                  labels=lbl)
        if "queue" in cols:
            queue = coll.series.latest(cols["queue"], labels=lbl)
        if "util" in cols:
            util = coll.series.latest(cols["util"], labels=lbl)
        rows.append((m["member"], m["kind"],
                     "up" if m["up"] else "DOWN",
                     _fmt_stat(qps), _fmt_stat(p50, "{:.4g}"),
                     _fmt_stat(p99, "{:.4g}"), _fmt_stat(queue),
                     _fmt_stat(util, "{:.2f}")))
    header = ("MEMBER", "KIND", "UP", "QPS", "P50", "P99", "QUEUE",
              "KV_UTIL")
    widths = [max([len(header[i])] + [len(r[i]) for r in rows])
              for i in range(len(header))]
    lines = ["  ".join(h.ljust(w) for h, w in zip(header, widths))]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    if not rows:
        lines.append("(no members announced yet)")
    return "\n".join(lines)


def format_straggler_lines(coll, window_s: float = 60.0,
                           flag_at: float = 3.0) -> str:
    """Comm-endpoint straggler scores for the `cli top` footer: one
    line per endpoint whose mean round time drifts above its peers',
    the SLO-able detector threshold marked.  Empty string when the
    fleet has no per-endpoint round data (no distributed training
    running, or a single pserver)."""
    from paddle_tpu.observability import attribution

    scores = attribution.straggler_scores(coll.series,
                                          window_s=window_s)
    drifted = {ep: s for ep, s in scores.items() if s > 0.5}
    if not drifted:
        return ""
    lines = ["stragglers (round-time z-score vs peers):"]
    for ep, s in sorted(drifted.items(), key=lambda t: -t[1]):
        mark = "  << STRAGGLER" if s >= flag_at else ""
        lines.append(f"  {ep}  {s:.1f}{mark}")
    return "\n".join(lines)


def cmd_top(argv):
    """`python -m paddle_tpu.cli top --registry HOST:PORT` — the live
    fleet table: every announced member (trainers, pservers, serving
    replicas, routers) with windowed qps, p50/p99 latency, queue depth
    and KV-pool utilization from a TelemetryCollector scrape, plus the
    SLO scoreboard when --slo points at a spec file.  One render after
    --samples scrapes by default; --watch refreshes until ^C."""
    import time as _time

    from paddle_tpu.observability import slo as slo_mod
    from paddle_tpu.observability.collector import TelemetryCollector

    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli top",
        description="live fleet telemetry table "
        "(docs/observability.md 'Fleet telemetry')")
    ap.add_argument("--registry", required=True,
                    help="TTL-lease registry HOST:PORT the fleet's "
                    "members announce() in")
    ap.add_argument("--period", type=float, default=0.5,
                    help="scrape period seconds")
    ap.add_argument("--samples", type=int, default=4,
                    help="scrapes before the (first) render — two or "
                    "more make windowed rates/quantiles meaningful")
    ap.add_argument("--window", type=float, default=60.0,
                    help="window seconds for qps/p50/p99")
    ap.add_argument("--slo", default="",
                    help="SLO spec file (tools/slo.json) to score "
                    "against the fleet series")
    ap.add_argument("--watch", action="store_true",
                    help="keep refreshing until interrupted")
    args = ap.parse_args(argv)

    coll = TelemetryCollector(registry_addr=args.registry,
                              period_s=args.period)
    specs = slo_mod.load_slos(args.slo) if args.slo else []
    try:
        while True:
            for i in range(max(args.samples, 1)):
                if i:  # sleep BETWEEN scrapes, never after the last
                    _time.sleep(args.period)
                coll.scrape_once()
            print(format_fleet_table(coll, window_s=args.window))
            straggler = format_straggler_lines(coll,
                                               window_s=args.window)
            if straggler:
                print(straggler)
            if specs:
                print()
                print(slo_mod.format_slo_table(
                    slo_mod.evaluate(specs, coll.series)))
            if not args.watch:
                break
            print()
            # --samples 1 never sleeps inside the scrape loop; without
            # this the watch loop would hammer every member endpoint
            _time.sleep(args.period)
    except KeyboardInterrupt:
        pass
    finally:
        coll.close()
    return 0


def cmd_slo(argv):
    """`python -m paddle_tpu.cli slo --check [--spec tools/slo.json]`
    — evaluate the fleet SLOs and exit nonzero on violation.  Two
    modes: `--registry HOST:PORT` samples a live fleet through a
    TelemetryCollector and applies the full multiwindow burn-rate rule;
    `--prom DUMP` gates a single Prometheus dump (federation output or
    any scrape) on lifetime stats — the CI smoke mode."""
    import time as _time

    from paddle_tpu.observability import slo as slo_mod

    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli slo",
        description="evaluate SLO specs against fleet telemetry "
        "(docs/observability.md 'Fleet telemetry')")
    ap.add_argument("--spec", default="tools/slo.json",
                    help="SLO spec file (grammar + dict forms)")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero when any objective alerts")
    ap.add_argument("--registry", default="",
                    help="live mode: scrape this fleet registry")
    ap.add_argument("--prom", default="",
                    help="snapshot mode: gate this Prometheus text "
                    "dump")
    ap.add_argument("--period", type=float, default=0.5)
    ap.add_argument("--samples", type=int, default=6,
                    help="live mode: scrapes before evaluating")
    args = ap.parse_args(argv)

    specs = slo_mod.load_slos(args.spec)
    if bool(args.registry) == bool(args.prom):
        raise SystemExit(
            "slo: give exactly one of --registry (live) or --prom "
            "(snapshot)")
    if args.prom:
        from paddle_tpu.observability.collector import \
            parse_prometheus_text

        with open(args.prom) as f:
            families = parse_prometheus_text(f.read())
        statuses = slo_mod.evaluate_snapshot(specs, families)
    else:
        from paddle_tpu.observability.collector import \
            TelemetryCollector

        coll = TelemetryCollector(registry_addr=args.registry,
                                  period_s=args.period)
        try:
            for i in range(max(args.samples, 2)):
                if i:  # sleep BETWEEN scrapes, never after the last
                    _time.sleep(args.period)
                coll.scrape_once()
            statuses = slo_mod.evaluate(specs, coll.series)
        finally:
            coll.close()
    print(slo_mod.format_slo_table(statuses))
    bad = slo_mod.failed(statuses)
    print(f"slo: {len(statuses)} objective(s) — "
          + ("FAILED" if bad else "all met"))
    if args.check and bad:
        return 1
    return 0


# ---------------------------------------------------------------------------
# `why` / `trace-of` subcommands: the time-attribution plane
# (docs/observability.md "Time attribution")
# ---------------------------------------------------------------------------


def cmd_why(argv):
    """`python -m paddle_tpu.cli why [--kind generation|trainer|
    pserver]` — the fleet "where does the time go" table: per
    (kind, member, phase) share of attributed time.  Two modes like
    `cli slo`: `--prom DUMP` reads lifetime sums from a federated
    Prometheus dump; `--registry HOST:PORT` scrapes a live fleet and
    shows windowed rates."""
    import time as _time

    from paddle_tpu.observability import attribution

    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli why",
        description="per-phase time attribution across the fleet "
        "(docs/observability.md 'Time attribution')")
    ap.add_argument("--kind", default="",
                    choices=[""] + list(attribution.KINDS),
                    help="restrict to one member kind")
    ap.add_argument("--prom", default="",
                    help="snapshot mode: a federated Prometheus dump")
    ap.add_argument("--registry", default="",
                    help="live mode: scrape this fleet registry")
    ap.add_argument("--period", type=float, default=0.5)
    ap.add_argument("--samples", type=int, default=4)
    ap.add_argument("--window", type=float, default=60.0)
    args = ap.parse_args(argv)

    kind = args.kind or None
    if bool(args.registry) == bool(args.prom):
        raise SystemExit("why: give exactly one of --registry (live) "
                         "or --prom (snapshot)")
    if args.prom:
        from paddle_tpu.observability.collector import \
            parse_prometheus_text

        with open(args.prom) as f:
            parsed = parse_prometheus_text(f.read())
        rows = attribution.why_rows_from_parsed(parsed, kind)
    else:
        from paddle_tpu.observability.collector import \
            TelemetryCollector

        coll = TelemetryCollector(registry_addr=args.registry,
                                  period_s=args.period)
        try:
            for i in range(max(args.samples, 2)):
                if i:
                    _time.sleep(args.period)
                coll.scrape_once()
            rows = attribution.why_rows(coll.series, kind,
                                        window_s=args.window)
        finally:
            coll.close()
    print(attribution.format_why_table(rows))
    return 0


def cmd_trace_of(argv):
    """`python -m paddle_tpu.cli trace-of --metric serving.request
    --prom DUMP [--trace-dir DIR]` — resolve a latency outlier to its
    trace: pick the histogram exemplar nearest the requested quantile
    (p99 by default) from a federated dump, and, when --trace-dir
    holds the fleet's trace/flight files, assemble the end-to-end
    Chrome trace for that trace id."""
    from paddle_tpu.observability import attribution
    from paddle_tpu.observability import slo as slo_mod
    from paddle_tpu.observability.collector import (
        assemble_traces, parse_prometheus_text)

    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli trace-of",
        description="histogram exemplar -> joined Chrome trace "
        "(docs/observability.md 'Time attribution')")
    ap.add_argument("--metric", required=True,
                    help="histogram family (short alias like "
                    "'serving.request' or full paddle_tpu_* name)")
    ap.add_argument("--prom", required=True,
                    help="federated Prometheus dump with exemplars")
    ap.add_argument("--p99", action="store_true",
                    help="target the p99 outlier (the default)")
    ap.add_argument("--q", type=float, default=0.99,
                    help="target quantile (overrides --p99)")
    ap.add_argument("--trace-dir", default="",
                    help="fleet trace dir: also write the joined "
                    "Chrome trace for the picked trace id")
    ap.add_argument("--out", default="",
                    help="output dir for the joined trace "
                    "(default: --trace-dir)")
    args = ap.parse_args(argv)

    with open(args.prom) as f:
        parsed = parse_prometheus_text(f.read())
    name = args.metric
    if name not in parsed:
        name = slo_mod.ALIASES.get(args.metric, name)
    if name not in parsed and not name.startswith("paddle_tpu_"):
        name = "paddle_tpu_" + name
    ex = attribution.pick_exemplar(parsed, name, q=args.q)
    if ex is None:
        print(f"trace-of: no exemplars on {name!r} — run the fleet "
              "with PADDLE_TPU_EXEMPLARS=on and PADDLE_TPU_TRACE=on")
        return 1
    qs = ex.get("quantile_s")
    print(f"metric   {name}")
    if qs is not None:
        print(f"p{args.q * 100:g}      {qs:.6g}s")
    print(f"exemplar {ex['value']:.6g}s  labels={ex['labels']}")
    print(f"trace_id {ex['trace_id']}")
    if args.trace_dir:
        joined = assemble_traces(args.trace_dir,
                                 args.out or args.trace_dir)
        path = joined.get(ex["trace_id"])
        if path:
            print(f"trace    {path}")
        else:
            print(f"trace    (trace_id not found under "
                  f"{args.trace_dir} — was the member running with "
                  "PADDLE_TPU_TRACE_DIR pointed there?)")
            return 1
    return 0


# ---------------------------------------------------------------------------
# `serve` subcommand: one generation replica (docs/serving.md)
# ---------------------------------------------------------------------------


def cmd_serve(argv):
    """`python -m paddle_tpu.cli serve MODEL_DIR [--port P]` — front a
    continuous-batching GenerationServer with the TCP replica protocol
    (serving/replica.py).  MODEL_DIR is a directory written by
    serving.save_generation_model (generation.json spec + params npz).
    With --registry (or PADDLE_TPU_REGISTRY), the replica registers
    under a TTL lease so a cloud.router.ReplicaRouter front door
    discovers, health-checks, and hot-swaps it."""
    import paddle_tpu as fluid
    from paddle_tpu.serving import ReplicaServer, server_from_model_dir

    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli serve",
        description="serve a saved generation model as one replica")
    ap.add_argument("model_dir", help="save_generation_model output dir")
    ap.add_argument("--port", type=int, default=0,
                    help="TCP port (0 = ephemeral, printed on start)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--slots", type=int, default=0,
                    help="decode slots (0 = model spec / default 8)")
    ap.add_argument("--kv_blocks", type=int, default=0,
                    help="KV pool blocks (0 = model spec / default 64)")
    ap.add_argument("--block_size", type=int, default=0,
                    help="KV block size in positions (0 = spec / 16)")
    ap.add_argument("--kv_dtype", default="",
                    help="KV pool precision: fp32|bf16|int8 ('' = "
                    "model spec / PADDLE_TPU_SERVING_KV_DTYPE / fp32); "
                    "docs/serving.md 'KV quantization'")
    ap.add_argument("--spec_k", type=int, default=0,
                    help="speculative draft tokens per tick (0 = model "
                    "spec / flag default; needs draft params in the "
                    "model dir)")
    ap.add_argument("--no_draft", action="store_true",
                    help="ignore draft params in the model dir "
                    "(disable speculative decoding)")
    ap.add_argument("--registry",
                    default=os.environ.get("PADDLE_TPU_REGISTRY", ""),
                    help="TTL-lease registry HOST:PORT to register "
                    "with (kind 'generation')")
    ap.add_argument("--ttl", type=float, default=2.0,
                    help="registry lease TTL seconds")
    ap.add_argument("--drain_grace", "--drain-grace", type=float,
                    default=30.0, dest="drain_grace",
                    help="graceful-SIGTERM drain budget seconds: on "
                    "SIGTERM the replica stops admission, releases "
                    "its lease, finishes in-flight streams within "
                    "this budget, delists telemetry, then exits "
                    "(docs/serving.md 'Autoscaling')")
    ap.add_argument("--cold", action="store_true",
                    help="ignore the model dir's warm-start xla_cache "
                    "artifact (compile from scratch — the baseline "
                    "the artifact is measured against)")
    ap.add_argument("--telemetry",
                    default=os.environ.get(
                        "PADDLE_TPU_TELEMETRY_REGISTRY", ""),
                    help="fleet telemetry registry HOST:PORT — the "
                    "replica announces its /metrics endpoint there "
                    "for a TelemetryCollector (docs/observability.md "
                    "'Fleet telemetry')")
    ap.add_argument("--use_tpu", type=int, default=1)
    args = ap.parse_args(argv)
    if args.telemetry:
        # ReplicaServer's env-gated maybe_announce() does the work
        os.environ["PADDLE_TPU_TELEMETRY_REGISTRY"] = args.telemetry

    server = server_from_model_dir(
        args.model_dir, slots=args.slots or None,
        kv_blocks=args.kv_blocks or None,
        block_size=args.block_size or None,
        kv_dtype=args.kv_dtype or None,
        spec_k=args.spec_k or None,
        use_draft=not args.no_draft,
        warm_start=not args.cold,
        place=_place(args.use_tpu))
    rep = ReplicaServer(server, port=args.port, host=args.host,
                        registry_addr=args.registry or None,
                        ttl_s=args.ttl,
                        drain_grace_s=args.drain_grace,
                        own_announcement=True)
    # graceful scale-in: SIGTERM drains before exit, chaining onto the
    # flight recorder's dump handler when PADDLE_TPU_FLIGHT_DIR is set
    rep.install_sigterm()
    suffix = (f", registered in {args.registry}" if args.registry
              else "")
    ws = server.warmup_stats
    if server.warm_start_dir:
        suffix += (f" (warm start: {ws['cache_hits']} executables "
                   f"deserialized, {ws['cache_misses']} compiled, "
                   f"warmup {ws['warmup_s']:.2f}s)")
    else:
        suffix += (f" (cold start: {ws['compiles']} compiles, "
                   f"warmup {ws['warmup_s']:.2f}s)")
    print(f"serving {args.model_dir} on {rep.addr}{suffix}", flush=True)
    try:
        rep.wait()
    except KeyboardInterrupt:
        pass
    finally:
        rep.close()
        server.close()
    return 0


# ---------------------------------------------------------------------------
# `autoscale` subcommand: the self-scaling serving front door
# ---------------------------------------------------------------------------


def cmd_autoscale(argv):
    """`python -m paddle_tpu.cli autoscale MODEL_DIR [--min 1 --max 4]`
    — run the ROADMAP-4 front door: a ReplicaRouter (hosting the
    TTL-lease replica registry unless --registry joins an existing
    one) plus an Autoscaler that spawns/retires `cli serve` replicas
    of MODEL_DIR from the router's windowed backlog/p99 signals
    (docs/serving.md "Autoscaling").  Prints a status line every
    --status_period seconds until interrupted; on exit the spawned
    replicas are retired gracefully."""
    import time as _time

    from paddle_tpu.cloud.autoscaler import (Autoscaler,
                                             AutoscalerPolicy,
                                             SubprocessReplicaLauncher)
    from paddle_tpu.cloud.router import ReplicaRouter

    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli autoscale",
        description="signal-driven autoscaling serving fleet")
    ap.add_argument("model_dir", help="save_generation_model output "
                    "dir (ship it with warm_start=True so scale-out "
                    "replicas skip XLA compile)")
    ap.add_argument("--registry", default="",
                    help="join an existing replica registry instead "
                    "of hosting one")
    ap.add_argument("--min", type=int, default=1, dest="min_replicas")
    ap.add_argument("--max", type=int, default=4, dest="max_replicas")
    ap.add_argument("--p99_high", type=float, default=2.0,
                    help="scale-out latency target seconds")
    ap.add_argument("--backlog_high", type=float, default=512,
                    help="scale-out reserved-token backlog threshold")
    ap.add_argument("--backlog_low", type=float, default=32,
                    help="scale-in idle backlog threshold (hysteresis "
                    "floor)")
    ap.add_argument("--sustain", type=float, default=3.0,
                    help="seconds the hot signal must hold")
    ap.add_argument("--idle_sustain", type=float, default=10.0,
                    help="seconds the cold signal must hold")
    ap.add_argument("--cooldown", type=float, default=15.0,
                    help="refractory seconds after any scale action")
    ap.add_argument("--poll", type=float, default=0.5)
    ap.add_argument("--window", type=float, default=15.0,
                    help="signal window seconds (router.signals)")
    ap.add_argument("--drain_grace", "--drain-grace", type=float,
                    default=30.0, dest="drain_grace")
    ap.add_argument("--spawn_timeout", type=float, default=300.0)
    ap.add_argument("--status_period", type=float, default=5.0)
    ap.add_argument("--use_tpu", type=int, default=1)
    args = ap.parse_args(argv)

    policy = AutoscalerPolicy(
        args.min_replicas, args.max_replicas,
        p99_high_s=args.p99_high, backlog_high=args.backlog_high,
        backlog_low=args.backlog_low, sustain_s=args.sustain,
        idle_sustain_s=args.idle_sustain, cooldown_s=args.cooldown)
    router = ReplicaRouter(registry_addr=args.registry or None,
                           desired=max(args.max_replicas * 2, 8))
    launcher = SubprocessReplicaLauncher(
        args.model_dir, router.registry_addr, use_tpu=args.use_tpu,
        drain_grace_s=args.drain_grace)
    scaler = Autoscaler(router, launcher, policy, poll_s=args.poll,
                        window_s=args.window,
                        spawn_timeout_s=args.spawn_timeout,
                        drain_grace_s=args.drain_grace)
    print(f"autoscale: fronting {args.model_dir}; replica registry at "
          f"{router.registry_addr} (band {args.min_replicas}.."
          f"{args.max_replicas})", flush=True)
    try:
        # inside the try: a Ctrl-C during the cold boot (the floor
        # replica can take minutes on the compile path) must still
        # reach the finally and retire whatever was already spawned
        scaler.ensure_min()
        scaler.start()
        while True:
            _time.sleep(args.status_period)
            st = scaler.status()
            sig = router.signals(args.window)
            print(f"autoscale: live={len(st['live'])} "
                  f"pending={st['pending_spawns']} "
                  f"qps={_fmt_stat(sig['qps'])} "
                  f"p99={_fmt_stat(sig['p99'], '{:.4g}')} "
                  f"backlog={_fmt_stat(sig['outstanding_tokens'])} "
                  f"| {st['last_event']}", flush=True)
    except KeyboardInterrupt:
        print("autoscale: retiring owned replicas", flush=True)
    finally:
        scaler.close(retire_owned=True)
        router.close()
    return 0


# ---------------------------------------------------------------------------
# `verify` subcommand: static analysis of saved / buildable programs
# ---------------------------------------------------------------------------


def _programs_from_target(path):
    """Yield (label, program, feed_names, fetch_names) for one verify
    target: a model dir saved by io.save_inference_model (`__model__`
    JSON), or a python file defining build() (CLI config contract, or an
    example-style build returning Program objects)."""
    import paddle_tpu as fluid

    if os.path.isdir(path):
        import json

        from paddle_tpu.io import MODEL_FILENAME

        model_path = os.path.join(path, MODEL_FILENAME)
        if not os.path.exists(model_path):
            raise SystemExit(
                f"{path!r} has no {MODEL_FILENAME} file — not a model "
                "dir saved by save_inference_model")
        with open(model_path) as f:
            payload = json.load(f)
        yield (f"{path}/{MODEL_FILENAME}",
               fluid.Program.from_dict(payload["program"]),
               payload.get("feed_var_names"),
               payload.get("fetch_var_names"))
        return

    mod = _load_config(path)
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        out = mod.build()
    # collect every Program the config touched: returned directly
    # (example-style tuples / dicts) or built under the ambient guard
    # (CLI config contract)
    seen = {}

    def add(label, prog):
        if isinstance(prog, fluid.Program) and id(prog) not in seen:
            seen[id(prog)] = (label, prog)

    if isinstance(out, dict):
        for k, v in out.items():
            add(f"{path}:{k}", v)
    elif isinstance(out, (list, tuple)):
        for i, v in enumerate(out):
            add(f"{path}:build()[{i}]", v)
    else:
        add(f"{path}:build()", out)
    add(f"{path}:main", main_p)
    add(f"{path}:startup", startup)
    for label, prog in seen.values():
        if prog.global_block().ops or len(prog.blocks) > 1:
            yield label, prog, None, None


def _diagnostics_json(diagnostics):
    """The shared machine-readable diagnostics list (`cli verify --json`
    and `cli analyze --json` emit the same shape): one dict per
    Diagnostic with severity / pass / location / hint
    (analysis.Diagnostic.to_dict), strongest severity first."""
    from paddle_tpu.analysis import severity_rank

    ordered = sorted(
        diagnostics,
        key=lambda d: (-severity_rank(d.severity), d.block_idx,
                       -1 if d.op_idx is None else d.op_idx))
    return [d.to_dict() for d in ordered]


def cmd_verify(argv):
    """`python -m paddle_tpu.cli verify TARGET... [--level error]
    [--json]` — run the static analyzer (paddle_tpu.analysis) over
    programs saved by io.py or built by config/example files; exit
    non-zero when any diagnostic reaches --level.  `--json` replaces the
    human report with one JSON document (diagnostics as a structured
    list) for CI and editor consumers."""
    import json

    from paddle_tpu.analysis import format_diagnostics, severity_rank

    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli verify",
        description="static analysis of Program IR (docs/analysis.md)")
    ap.add_argument("targets", nargs="+",
                    help="model dir (save_inference_model output) or "
                    "python file defining build()")
    ap.add_argument("--level", default="error",
                    choices=["error", "warn", "info"],
                    help="minimum severity that fails the check")
    ap.add_argument("--passes", default="",
                    help="comma-separated pass ids (default: all)")
    ap.add_argument("--show", default="warning",
                    choices=["error", "warning", "info"],
                    help="minimum severity to print")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document instead of the human "
                    "report (machine-readable diagnostics)")
    args = ap.parse_args(argv)

    passes = [p for p in args.passes.split(",") if p] or None
    fail_rank = severity_rank(
        "warning" if args.level == "warn" else args.level)
    n_programs = 0
    failed = False
    results = []
    for target in args.targets:
        for label, prog, feeds, fetches in _programs_from_target(target):
            n_programs += 1
            diagnostics = prog.verify(level=None, passes=passes,
                                      feed_names=feeds,
                                      fetch_names=fetches)
            bad = [d for d in diagnostics
                   if severity_rank(d.severity) >= fail_rank]
            failed = failed or bool(bad)
            if args.json:
                results.append({
                    "target": target,
                    "label": label,
                    "status": "fail" if bad else "ok",
                    "diagnostics": _diagnostics_json(diagnostics),
                })
                continue
            shown = [d for d in diagnostics
                     if severity_rank(d.severity)
                     >= severity_rank(args.show)]
            status = "FAIL" if bad else "ok"
            print(f"[{status}] {label}: {len(diagnostics)} diagnostic(s)")
            if shown:
                print(format_diagnostics(shown))
    if not n_programs:
        raise SystemExit("verify: no programs found in the given targets")
    if args.json:
        print(json.dumps({"level": args.level, "failed": failed,
                          "programs": results}, indent=1))
    else:
        print(f"verify: {n_programs} program(s) checked — "
              + ("FAILED" if failed else "all clean at level "
                 + args.level))
    return 1 if failed else 0


# ---------------------------------------------------------------------------
# `analyze` subcommand: static cost / roofline / comm / budget gate
# ---------------------------------------------------------------------------


def _load_budgets(path):
    import json

    with open(path) as f:
        budgets = json.load(f)
    if not isinstance(budgets.get("models", None), dict):
        raise SystemExit(
            f"budget file {path!r} must be "
            "{'defaults': {...}, 'models': {target: {...}}} "
            "(docs/analysis.md 'Budget gate')")
    return budgets


def _budget_for(budgets, target):
    """Budget entry for one analyze target: exact key match on the
    target as given, else on its basename — overlaid on 'defaults'."""
    models = budgets.get("models", {})
    entry = models.get(target)
    if entry is None:
        entry = models.get(os.path.basename(target))
    if entry is None:
        return None
    return {**budgets.get("defaults", {}), **entry}


def cmd_analyze(argv):
    """`python -m paddle_tpu.cli analyze TARGET... [--json]
    [--budget budgets.json] [--batch N]` — the compile-free cost
    report: static roofline (FLOPs, HBM traffic, arithmetic intensity
    vs the device ridge point, memory/compute-bound verdict), the
    liveness-based peak-HBM estimate, per-mesh-axis comm volume, and
    the cost/collective diagnostics, for every program a target builds
    — plus generation model dirs (generation.json), costed from the
    serving-kernel entries without building a decoder.

    With `--budget`, each target's headline program is gated against
    its checked-in budget entry and the exit status is non-zero on any
    violation — a perf-regression gate that never invokes XLA
    (docs/analysis.md 'Budget gate')."""
    import json as _json

    from paddle_tpu import analysis
    from paddle_tpu.analysis import cost_model

    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli analyze",
        description="static cost analysis of Program IR / generation "
        "model dirs (docs/analysis.md)")
    ap.add_argument("targets", nargs="+",
                    help="config/example file defining build(), model "
                    "dir (save_inference_model output), or generation "
                    "model dir (save_generation_model output)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document (shares the verify "
                    "--json diagnostics shape)")
    ap.add_argument("--budget", default="",
                    help="budgets.json path: exit non-zero when an "
                    "estimate exceeds its checked-in budget")
    ap.add_argument("--batch", type=int,
                    default=cost_model.DEFAULT_BATCH,
                    help="batch size substituted for -1 dims "
                    f"(default {cost_model.DEFAULT_BATCH})")
    ap.add_argument("--device", default=cost_model.DEFAULT_DEVICE,
                    choices=sorted(cost_model.DEVICE_SPECS),
                    help="ridge-point device (default: the bench chip)")
    ap.add_argument("--top", type=int, default=5,
                    help="top-N traffic-heavy ops to list per program")
    args = ap.parse_args(argv)

    budgets = _load_budgets(args.budget) if args.budget else None
    out = {"programs": [], "violations": []}
    n_targets = 0

    for target in args.targets:
        spec_path = os.path.join(target, "generation.json") \
            if os.path.isdir(target) else ""
        if spec_path and os.path.exists(spec_path):
            n_targets += 1
            with open(spec_path) as f:
                spec = _json.load(f)
            rep = analysis.analyze_generation_spec(spec,
                                                   device=args.device)
            out["programs"].append({"target": target,
                                    "kind": "generation",
                                    "report": rep})
            if not args.json:
                _print_generation_report(target, rep)
            if budgets is not None and _budget_for(budgets,
                                                  target) is not None:
                # fail loudly rather than silently skipping a budget
                # the operator checked in
                out["violations"].append(
                    f"{target}: budget entries for generation model "
                    "dirs are not supported (budgets gate Program "
                    "targets)")
            continue

        headline = None
        target_unknown: dict = {}
        for label, prog, feeds, fetches in _programs_from_target(target):
            n_targets += 1
            est = analysis.estimate_program(
                prog, batch_size=args.batch, feed_names=feeds,
                fetch_names=fetches, device=args.device)
            if (not est.total_flops and not est.total_bytes
                    and not est.unknown_types):
                continue  # empty program: no roofline signal.  A
                # program whose ops are all cost-UNKNOWN must NOT be
                # skipped — that is the coverage regression the
                # max_unknown_ops budget floor exists to catch
            comm = analysis.estimate_comm(
                prog, batch_size=args.batch,
                fetch_names=fetches).by_axis()
            # collective-safety only: the cost-model/comm-volume pass
            # output would just re-derive the `est`/`comm` tables this
            # report already carries (and re-run the liveness walk)
            diagnostics = prog.verify(
                level=None, passes=["collective-safety"],
                feed_names=feeds, fetch_names=fetches)
            rep = {
                "target": target,
                "label": label,
                "kind": "program",
                "roofline": est.roofline(),
                "comm": comm,
                "top_traffic_ops": [
                    {"block": b, "op": i, "type": t, "ai": ai,
                     "bytes": by}
                    for b, i, t, ai, by in est.top_memory_bound(args.top)
                ],
                "diagnostics": _diagnostics_json(diagnostics),
            }
            out["programs"].append(rep)
            for t, c in est.unknown_types.items():
                target_unknown[t] = target_unknown.get(t, 0) + c
            if headline is None or (est.total_flops
                                    > headline[1].total_flops):
                headline = (rep, est)
            if not args.json:
                _print_program_report(rep)

        if budgets is not None:
            budget = _budget_for(budgets, target)
            if headline is None:
                if budget is not None:
                    # a budgeted target with nothing analyzable is a
                    # failure, not a silent pass (the config may have
                    # stopped building, or every op lost its metadata)
                    out["violations"].append(
                        f"{target}: has a budget entry but produced no "
                        "analyzable program")
            elif budget is None:
                out["violations"].append(
                    f"{target}: no budget entry in {args.budget} "
                    "(add one under 'models')")
            else:
                # flops/traffic/peak limits gate the headline program
                # (budgets are seeded from it), but the COVERAGE floor
                # is target-wide: an unknown-cost op in ANY program of
                # the target is the regression max_unknown_ops catches
                gated = dict(headline[0])
                gated["roofline"] = {
                    **headline[0]["roofline"],
                    "unknown_ops": sum(target_unknown.values()),
                    "unknown_types": sorted(target_unknown),
                }
                for v in analysis.check_budget(gated, budget):
                    out["violations"].append(f"{target}: {v}")

    if not n_targets:
        raise SystemExit("analyze: no programs found in the given "
                         "targets")
    if args.json:
        print(_json.dumps(out, indent=1, default=float))
    else:
        for v in out["violations"]:
            print(f"BUDGET VIOLATION: {v}")
        print(f"analyze: {n_targets} program(s)"
              + (f", {len(out['violations'])} budget violation(s)"
                 if budgets is not None else "")
              + (" — FAILED" if out["violations"] else ""))
    return 1 if out["violations"] else 0


def _print_program_report(rep):
    roof = rep["roofline"]
    print(f"== {rep['label']} ==")
    line = (f"  flops {roof['est_flops'] / 1e9:.2f} G"
            f"  traffic {roof['est_hbm_traffic_gb']} GB")
    if "ai_flop_per_byte" in roof:
        line += (f"  AI {roof['ai_flop_per_byte']} vs ridge "
                 f"{roof['ridge_flop_per_byte']} flop/B "
                 f"({roof['device']}) -> {roof['bound']}-bound")
    print(line)
    print(f"  est peak HBM {roof['est_peak_hbm_gb']} GB  "
          f"(batch {roof['batch_size']} assumed, {roof['n_ops']} ops)")
    if roof["unknown_ops"]:
        print(f"  coverage: {roof['unknown_ops']} op(s) without cost "
              f"metadata: {roof['unknown_types']}")
    for axis, kinds in sorted(rep["comm"].items()):
        detail = ", ".join(f"{k} {b / 1e6:.3f} MB"
                           for k, b in sorted(kinds.items()))
        print(f"  comm[{axis}]: {detail}")
    if rep["top_traffic_ops"]:
        tops = ", ".join(
            f"{t['type']}@{t['block']}:{t['op']} "
            f"({t['bytes'] / 1e6:.1f} MB, AI {t['ai']})"
            for t in rep["top_traffic_ops"][:3])
        print(f"  heaviest traffic: {tops}")
    errors = [d for d in rep["diagnostics"] if d["severity"] == "error"]
    for d in errors:
        print(f"  [error] {d['pass']}: {d['message']}")


def _print_generation_report(target, rep):
    print(f"== {target} (generation model dir) ==")
    m = rep["model"]
    print(f"  d_model {m['d_model']}  layers {m['n_layers']}  vocab "
          f"{m['vocab_size']}  kv_dtype {m['kv_dtype']}  slots "
          f"{m['slots']}")
    print(f"  params {rep['param_bytes'] / 1e6:.1f} MB  KV "
          f"{rep['bytes_per_block'] / 1e3:.1f} kB/block")
    for k in rep["kernels"]:
        line = (f"  {k['kernel']}: {k['flops'] / 1e6:.2f} MFLOP, "
                f"{k['bytes'] / 1e6:.2f} MB/tick")
        if "ai_flop_per_byte" in k:
            line += (f", AI {k['ai_flop_per_byte']} vs ridge "
                     f"{k['ridge_flop_per_byte']} -> {k['bound']}-bound")
        print(line)


# ---------------------------------------------------------------------------
# `concurrency` subcommand: lock-order/race lint + schedule checking
# ---------------------------------------------------------------------------


def cmd_concurrency(argv):
    """`python -m paddle_tpu.cli concurrency [PATHS...] [--json]
    [--sched] [--rules r1,r2]` — the whole-repo AST concurrency
    analyzer (docs/analysis.md "Concurrency analysis"): lock inventory,
    lock-order cycles, blocking-calls-under-lock, RacerD-style
    unguarded-attribute races, thread hygiene.  Exit non-zero on any
    UNSUPPRESSED error-severity finding (`# lint: <rule>-ok` comments
    demote to info).

    `--sched` additionally runs the fast deterministic-schedule-checker
    protocol subset (analysis/schedmodels.py): FENCE->MIGRATE->COMMIT,
    elastic_round replay, GenerationServer admit/finish/swap over the
    real PagedKVCache, and CommPool.send_round ordering — each must
    hold its invariant over every explored interleaving."""
    import json

    from paddle_tpu.analysis import concurrency as conc

    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli concurrency",
        description="AST concurrency lint + schedule checking "
        "(docs/analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the whole "
                    "paddle_tpu package)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document (shares the verify "
                    "--json diagnostics shape)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule subset "
                    f"(default: all of {', '.join(conc.RULES)})")
    ap.add_argument("--sched", action="store_true",
                    help="also run the schedule-checker protocol "
                    "models (a few seconds)")
    ap.add_argument("--sched-schedules", type=int, default=120,
                    help="bounded-DFS schedule budget per protocol")
    ap.add_argument("--show", default="warning",
                    choices=["error", "warning", "info"],
                    help="minimum severity to print (human mode)")
    args = ap.parse_args(argv)

    rules = [r for r in args.rules.split(",") if r] or None
    if rules:
        unknown = sorted(set(rules) - set(conc.RULES))
        if unknown:
            # a typo'd rule must not silently verify nothing
            raise SystemExit(
                f"concurrency: unknown rule(s) {unknown}; "
                f"valid: {', '.join(conc.RULES)}")
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        # a typo'd path must not silently verify nothing either
        raise SystemExit(f"concurrency: no such path(s): {missing}")
    findings = conc.analyze_paths(args.paths or None, rules=rules)
    errors = [f for f in findings if f.severity == "error"]

    sched_results = []
    if args.sched:
        from paddle_tpu.analysis import schedcheck, schedmodels

        for name, (factory, inv) in schedmodels.PROTOCOLS.items():
            res = schedcheck.explore(
                factory(), inv,
                max_schedules=args.sched_schedules,
                random_schedules=30)
            sched_results.append(
                {"protocol": name, "schedules": res.schedules,
                 "ok": res.ok,
                 "violation": (str(res.violation)
                               if res.violation else None)})

    failed = bool(errors) or any(not r["ok"] for r in sched_results)
    if args.json:
        from paddle_tpu.analysis.concurrency import to_diagnostics

        print(json.dumps({
            "failed": failed,
            "summary": conc.summarize(findings),
            "diagnostics": [d.to_dict()
                            for d in to_diagnostics(findings)],
            "schedcheck": sched_results,
        }, indent=1))
        return 1 if failed else 0

    order = {"error": 0, "warning": 1, "info": 2}
    shown = [f for f in findings
             if order[f.severity] <= order[args.show]]
    for f in sorted(shown, key=lambda f: (order[f.severity], f.file,
                                          f.line)):
        print(f)
        if f.hint:
            print(f"    hint: {f.hint}")
    for r in sched_results:
        status = "ok" if r["ok"] else "FAIL"
        print(f"schedcheck {r['protocol']}: [{status}] "
              f"{r['schedules']} schedule(s) explored")
        if r["violation"]:
            print(f"    {r['violation']}")
    print(f"concurrency: {conc.summarize(findings)}"
          + (f"; {len(sched_results)} protocol(s) schedule-checked"
             if sched_results else "")
          + (" — FAILED" if failed else ""))
    return 1 if failed else 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    subcommands = {"verify": cmd_verify, "analyze": cmd_analyze,
                   "metrics": cmd_metrics, "trace": cmd_trace,
                   "serve": cmd_serve, "autoscale": cmd_autoscale,
                   "concurrency": cmd_concurrency,
                   "top": cmd_top, "slo": cmd_slo,
                   "why": cmd_why, "trace-of": cmd_trace_of}
    if argv and argv[0] in subcommands:
        sys.exit(subcommands[argv[0]](argv[1:]))
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.cli",
        description="legacy `paddle train` workflow over Program/Executor"
        " (plus subcommands: `python -m paddle_tpu.cli "
        "verify|analyze|concurrency|metrics|trace|serve|autoscale|"
        "top|slo|why|trace-of --help`)")
    ap.add_argument("--config", required=True, help="python config file "
                    "defining build()")
    ap.add_argument("--job", default="train",
                    choices=["train", "test", "checkgrad", "time", "merge"])
    ap.add_argument("--use_tpu", type=int, default=1,
                    help="1: default device (TPU when present); 0: CPU "
                    "interpreter-capable place (reference --use_gpu)")
    ap.add_argument("--num_passes", type=int, default=1)
    ap.add_argument("--batches_per_pass", type=int, default=0,
                    help="0 = drain the reader")
    ap.add_argument("--log_period", type=int, default=100)
    ap.add_argument("--save_dir", default="",
                    help="per-pass param dirs (ParamUtil) / merge output")
    ap.add_argument("--init_model_path", default="",
                    help="load persistables before the job (--start_pass "
                    "resume analogue)")
    ap.add_argument("--checkgrad_eps", type=float, default=1e-3)
    ap.add_argument("--checkgrad_samples", type=int, default=8)
    ap.add_argument("--checkgrad_tol", type=float, default=1e-2)
    args = ap.parse_args(argv)

    mod = _load_config(args.config)
    cfg = _build(mod)
    {"train": job_train, "test": job_test, "time": job_time,
     "checkgrad": job_checkgrad, "merge": job_merge}[args.job](cfg, args)


if __name__ == "__main__":
    main()
