"""TCP front for one GenerationServer process — the replica side of the
multi-replica front door (paddle_tpu/cloud/router.py).

Wire protocol: one JSON object per line, newline-delimited both ways
(the registry/cluster line-protocol convention, sized for control
traffic — tokens are a few bytes each and generation is compute-bound,
so a text protocol costs nothing measurable):

  {"op":"generate","prompt":[..],"max_new":8,"temperature":0,
   "seed":0,"eos_id":null,"deadline_ms":null,"skip":0}
      -> {"tok":17} per generated token (the first `skip` tokens are
         recomputed but NOT re-sent — the router's resume path after a
         replica death: decode is deterministic per (prompt, seed), so
         the survivor regenerates the same stream and the client never
         sees a duplicate), then {"done":true,"n":<generated>}
      -> {"err":"...","shed":true}  (deadline/saturation shed — a
         POLICY answer, the router must not retry it)
      -> {"err":"...","fatal":true} (caller error, e.g. over-capacity
         request — retrying elsewhere cannot help)
      -> {"err":"..."}              (replica-local failure — the router
         retries on a survivor)
  {"op":"ping"}   -> {"ok":true,"outstanding":N,"free_blocks":F,
                      "draining":false,"warm_start":false}
  {"op":"stats"}  -> {"ok":true,"stats":{...}}
  {"op":"flight"} -> {"ok":true,"dump":{...}}  (the process flight-
                     recorder ring: recent spans/events/metric
                     snapshots, observability/flightrecorder.py)
  {"op":"swap","dir":"..."} -> {"ok":true} after drain+swap+resume
  {"op":"drain","timeout":30} -> {"ok":true,"drained":true} — stop
                     ADMISSION and (by default) wait for every
                     accepted request to finish: the graceful-scale-in
                     verb the autoscaler calls before retiring a
                     replica ({"wait":false} just flips the flag)
  {"op":"resume"} -> {"ok":true} — re-open admission (aborted scale-in)
  {"op":"stop"}   -> {"ok":true}, then the replica shuts down

A replica registers itself in the front door's TTL-lease registry
(kind "generation") and holds the lease for its lifetime: lease expiry
IS the health check — a SIGKILLed replica vanishes from the routing
table within one TTL.  A SIGTERMed replica (scale-in, rolling restart)
dies GRACEFULLY when `install_sigterm()` is armed (`cli serve` does):
stop admission -> release the lease (delist from routing) -> drain
in-flight streams -> delist the telemetry announcement -> exit — the
front door never mistakes a scale-in for a death.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import threading
import time
from typing import Iterator, Optional

from ..core.resilience import fault_injector
from ..observability import tracing as obs_tracing
from .batching import RequestDeadlineExceeded, ServerSaturated

__all__ = ["ReplicaServer", "ReplicaError", "ReplicaShed",
           "replica_call", "replica_stream"]


class ReplicaError(RuntimeError):
    """The replica answered with a non-shed error (`fatal` marks caller
    errors that must not be retried on another replica)."""

    def __init__(self, message: str, fatal: bool = False):
        super().__init__(message)
        self.fatal = fatal


class ReplicaShed(RequestDeadlineExceeded):
    """The replica shed the request (deadline/saturation policy)."""


class ReplicaServer:
    """Serve one GenerationServer over TCP; optionally hold a TTL lease
    in a registry so the router can discover and health-check it."""

    def __init__(self, server, port: int = 0, host: str = "127.0.0.1",
                 registry_addr: Optional[str] = None,
                 kind: str = "generation", ttl_s: float = 2.0,
                 drain_grace_s: float = 30.0,
                 own_announcement: bool = False):
        self._server = server
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self.addr = f"{host}:{self.port}"
        self._stop = threading.Event()
        self._drain_grace_s = float(drain_grace_s)
        self._prev_sigterm = None
        # in-flight generate CONNECTIONS (distinct from the scheduler's
        # active set: the scheduler can be drained while a handler
        # thread is still flushing a stream's tail to a slow client)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._lease = None
        if registry_addr:
            # lazy import: the registry rides the native lib, which a
            # plain in-process server never needs
            from ..cloud.registry import Lease, RegistryClient

            self._lease = Lease(RegistryClient(registry_addr), kind,
                                self.addr, ttl_s=ttl_s)
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._accept_thread.start()
        # fleet telemetry: with PADDLE_TPU_TELEMETRY_REGISTRY set, the
        # replica publishes its /metrics endpoint for the
        # TelemetryCollector (no-op otherwise).  The announcement is
        # PROCESS-global (maybe_announce returns one shared handle), so
        # a graceful shutdown only delists it when this replica OWNS
        # the process (`cli serve` passes own_announcement=True) — an
        # embedded replica retiring must not remove a still-serving
        # process from the collector's member table.
        from ..observability.collector import maybe_announce

        self._own_announcement = bool(own_announcement)
        self._announcement = maybe_announce(kind)

    # -- server side --------------------------------------------------------
    def _accept(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()

    def _handle(self, conn: socket.socket):
        try:
            f = conn.makefile("rw", newline="\n")
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                except ValueError:
                    self._reply(f, {"err": "malformed request",
                                    "fatal": True})
                    continue
                if not self._dispatch(f, req):
                    break
        except (OSError, ValueError):
            pass  # client went away mid-reply; nothing to deliver
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _reply(f, obj) -> None:
        # default=str: flight dumps carry arbitrary span attrs / note
        # payloads (numpy scalars, exceptions) — the post-mortem path
        # must not die on an unserializable ring entry
        f.write(json.dumps(obj, separators=(",", ":"), default=str)
                + "\n")
        f.flush()

    def _dispatch(self, f, req) -> bool:
        op = req.get("op")
        if op == "generate":
            self._op_generate(f, req)
        elif op == "ping":
            self._reply(f, {
                "ok": True,
                "outstanding": self._server.outstanding_tokens(),
                "free_blocks": self._server._cache.free_blocks,
                "draining": (self._server.draining
                             or self._server._pending_states
                             is not None),
                "warm_start": bool(getattr(self._server,
                                           "warm_start_dir", None))})
        elif op == "stats":
            self._reply(f, {"ok": True, "stats": self._server.stats()})
        elif op == "flight":
            from ..observability import flightrecorder

            self._reply(f, {"ok": True,
                            "dump": flightrecorder.dump_dict(
                                reason="wire")})
        elif op == "swap":
            try:
                fault_injector().fire("serving.replica_swap")
                from .generation import load_generation_model

                states, _, draft_states = load_generation_model(
                    req["dir"], with_draft=True)
                # refresh the draft alongside the target when both
                # sides have one: a stale draft stays correct but its
                # accept rate against the new checkpoint can collapse
                # — a silent throughput regression on every swap
                if getattr(self._server, "_draft", None) is None:
                    draft_states = None
                ok = self._server.swap_states(
                    states, draft_states=draft_states,
                    wait=True, timeout=req.get("timeout", 120))
                self._reply(f, {"ok": bool(ok)})
            except Exception as e:
                self._reply(f, {"err": f"swap failed: {e!r}"})
        elif op == "drain":
            try:
                drained = self._server.drain(
                    wait=bool(req.get("wait", True)),
                    timeout=req.get("timeout", self._drain_grace_s))
                self._reply(f, {"ok": True, "drained": bool(drained),
                                "draining": True})
            except RuntimeError as e:  # already closed
                self._reply(f, {"err": str(e)})
        elif op == "resume":
            try:
                self._server.resume()
                self._reply(f, {"ok": True})
            except Exception as e:
                self._reply(f, {"err": f"resume failed: {e!r}"})
        elif op == "stop":
            self._reply(f, {"ok": True})
            self.close()
            return False
        else:
            self._reply(f, {"err": f"unknown op {op!r}", "fatal": True})
        return True

    def _op_generate(self, f, req):
        with self._inflight_lock:
            self._inflight += 1
        try:
            self._op_generate_inner(f, req)
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def _op_generate_inner(self, f, req):
        # join the router's trace: the propagated context (riding the
        # request JSON) parents this replica-side span — and, through
        # submit()'s context capture, the generation server's own
        # serving.request span — under the front door's root span, so
        # `cli trace-of` shows one tree across the three processes
        with obs_tracing.activate(
                obs_tracing.extract(req.get("trace"))), \
                obs_tracing.span("replica.generate",
                                 max_new=int(req["max_new"])):
            self._op_generate_traced(f, req)

    def _op_generate_traced(self, f, req):
        try:
            stream = self._server.submit(
                req["prompt"], int(req["max_new"]),
                temperature=float(req.get("temperature", 0.0)),
                seed=int(req.get("seed", 0)),
                eos_id=req.get("eos_id"),
                deadline_ms=req.get("deadline_ms"))
        except ServerSaturated as e:
            self._reply(f, {"err": str(e), "shed": True})
            return
        except ValueError as e:
            # caller error (e.g. over-capacity request): no other
            # replica can serve it either — don't retry
            self._reply(f, {"err": str(e), "fatal": True})
            return
        except RuntimeError as e:
            # replica-local state (server closing mid-accept during a
            # rolling restart): a SURVIVOR can serve this — retryable
            self._reply(f, {"err": str(e)})
            return
        skip = int(req.get("skip", 0))
        n = 0
        try:
            for tok in stream:
                n += 1
                if n > skip:
                    self._reply(f, {"tok": tok})
            self._reply(f, {"done": True, "n": n})
        except RequestDeadlineExceeded as e:
            self._reply(f, {"err": str(e), "shed": True})
        except Exception as e:
            self._reply(f, {"err": repr(e)})

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the replica is stopped (a remote `stop` op or
        close()); the `cli serve` foreground loop."""
        return self._stop.wait(timeout)

    # -- graceful termination (scale-in / SIGTERM) --------------------------
    def install_sigterm(self, grace_s: Optional[float] = None) -> bool:
        """Arm graceful SIGTERM handling, CHAINING onto whatever
        handler is already installed — when the flight recorder is
        armed (PADDLE_TPU_FLIGHT_DIR), its dump-and-redeliver hook
        still runs after the drain, so a terminated replica leaves
        both a clean fleet AND a post-mortem ring.  Main-thread only
        (signal.signal's rule); returns False when it could not be
        installed."""
        if grace_s is not None:
            self._drain_grace_s = float(grace_s)
        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM,
                                               self._on_sigterm)
        except (ValueError, OSError):  # not the main thread
            self._prev_sigterm = None
            return False
        return True

    def _on_sigterm(self, signum, frame):
        self.shutdown_gracefully(self._drain_grace_s)
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)  # e.g. the flight recorder's dump hook
        elif prev == signal.SIG_IGN:
            return
        else:
            # restore the default disposition and re-deliver so the
            # process still dies OF SIGTERM (exit status intact)
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    def shutdown_gracefully(self, grace_s: float = 30.0) -> None:
        """The scale-in exit sequence (docs/serving.md 'Autoscaling'):

        1. stop ADMISSION (new generate ops answer a retryable error,
           so a front-door router resubmits on a survivor);
        2. release the registry lease — the replica delists from the
           routing table immediately instead of looking like a death
           whose TTL expiry trips router retries;
        3. drain: every accepted request runs to completion and its
           handler thread finishes flushing the stream (bounded by
           `grace_s`; whatever is left past the grace is cut off and
           resumed by the router on a survivor — still zero failed);
        4. delist the telemetry announcement (this process's /metrics
           endpoint leaves the collector's member table cleanly);
        5. close the listener.

        Idempotent; called by the SIGTERM chain and usable directly."""
        if self._stop.is_set():
            return
        deadline = time.monotonic() + float(grace_s)
        try:
            self._server.drain(wait=False)
        except RuntimeError:
            pass  # server already closed: nothing to drain
        if self._lease is not None:
            self._lease.release()
        try:
            self._server.drain(
                wait=True, timeout=max(0.0,
                                       deadline - time.monotonic()))
        except RuntimeError:
            pass
        # scheduler drained; let handler threads flush stream tails
        while time.monotonic() < deadline:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.02)
        ann, self._announcement = self._announcement, None
        if ann is not None and self._own_announcement:
            ann.close()
        self.close()

    def close(self):
        if self._stop.is_set():
            return
        self._stop.set()
        if self._lease is not None:
            self._lease.release()
        # shutdown BEFORE close (the PR 7 VariableServer lesson): the
        # accept thread blocked in accept() holds the kernel's open
        # file description, so a bare close() leaves the port
        # LISTENING until one more client connects and gets served by
        # a supposedly-stopped replica — shutdown wakes the accept
        # immediately instead
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # never accepted / already gone
        try:
            self._sock.close()
        except OSError:
            pass


# -- client helpers (used by the router and tests) ---------------------------

def _connect(addr: str, timeout_s: float):
    host, port = addr.rsplit(":", 1)
    return socket.create_connection((host, int(port)),
                                    timeout=timeout_s)


def replica_call(addr: str, obj: dict, timeout_s: float = 30.0) -> dict:
    """One request, one JSON reply (ping/stats/swap/stop)."""
    with _connect(addr, timeout_s) as s:
        f = s.makefile("rw", newline="\n")
        f.write(json.dumps(obj, separators=(",", ":")) + "\n")
        f.flush()
        line = f.readline()
        if not line:
            raise OSError(f"replica {addr} closed connection")
        return json.loads(line)


def replica_stream(addr: str, obj: dict,
                   timeout_s: float = 120.0) -> Iterator[int]:
    """Stream a generate request's tokens; raises ReplicaShed on a
    policy shed, ReplicaError on replica-reported failure, OSError when
    the replica dies mid-stream (the router's retry trigger)."""
    with _connect(addr, timeout_s) as s:
        f = s.makefile("rw", newline="\n")
        f.write(json.dumps(obj, separators=(",", ":")) + "\n")
        f.flush()
        while True:
            line = f.readline()
            if not line:
                raise OSError(
                    f"replica {addr} died mid-stream")
            msg = json.loads(line)
            if "tok" in msg:
                yield int(msg["tok"])
            elif msg.get("done"):
                return
            elif "err" in msg:
                if msg.get("shed"):
                    raise ReplicaShed(msg["err"])
                raise ReplicaError(msg["err"],
                                   fatal=bool(msg.get("fatal")))
            else:
                raise ReplicaError(f"unexpected reply {msg!r}")
