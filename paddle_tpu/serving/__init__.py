"""Production inference serving.

`paddle_tpu.serving` grew from a single module (resident dynamic
batching for one-shot requests, PR 4's `InferenceServer`) into the
serving subsystem; the import path is unchanged, so every existing
``from paddle_tpu.serving import InferenceServer`` keeps working:

* **batching** — the original resident server: AOT-compiled batch-size
  buckets, window-coalesced dynamic batching, deadline shedding.  The
  right tool for stateless one-shot models (image classifiers).
* **kv_cache** — the paged KV-cache: fixed-size blocks carved out of
  ONE preallocated HBM pool, per-sequence block tables, alloc/free at
  sequence admit/finish.  Long and short sequences share the pool
  without fragmentation (the vLLM PagedAttention memory design), and
  fully-filled prompt blocks are hash-consed so sequences with a
  shared prefix SHARE blocks (refcounted, LRU-evicted when idle) and
  skip the shared prefill entirely.
* **generation** — `GenerationServer`: continuous (in-flight) batching
  for autoregressive decode.  One resident decode step per tick over
  the active sequence set; new requests are admitted into free slots
  BETWEEN ticks (prefill folded into the same per-token step), finished
  sequences are evicted immediately, admission is keyed to free KV
  blocks, and every request streams tokens through its own future.
  Optionally speculative: a small draft model proposes k tokens per
  tick and the target verifies the window in one dispatch (greedy
  output bit-identical by construction).  The KV pool stores fp32,
  bf16 or int8 blocks (`kv_dtype`) — quantize-on-write, dequantize-
  on-gather — trading tolerance for 2-4x the resident sequences.
* **replica** — a TCP front for one `GenerationServer` process
  (JSON-line protocol: generate/ping/swap/stats) so replicas can be
  health-checked, drained, and hot-swapped remotely.

The multi-replica front door (TTL-lease registered replicas,
least-outstanding-tokens placement, retry-on-death, zero-downtime
checkpoint hot-swap) lives in `paddle_tpu.cloud.router`.

See docs/serving.md for the architecture and runbook.
"""
from .batching import (InferenceServer, RequestDeadlineExceeded,
                       ServerSaturated)
from .generation import (GenerationServer, GenerationStream,
                         build_warm_start_artifact,
                         load_generation_model, save_generation_model,
                         server_from_model_dir)
from .kv_cache import KVPoolExhausted, PagedKVCache
from .replica import (ReplicaError, ReplicaServer, ReplicaShed,
                      replica_call, replica_stream)

__all__ = [
    "InferenceServer",
    "ServerSaturated",
    "RequestDeadlineExceeded",
    "PagedKVCache",
    "KVPoolExhausted",
    "GenerationServer",
    "GenerationStream",
    "save_generation_model",
    "load_generation_model",
    "server_from_model_dir",
    "build_warm_start_artifact",
    "ReplicaServer",
    "ReplicaError",
    "ReplicaShed",
    "replica_call",
    "replica_stream",
]
