"""Paged KV-cache: block-granular memory management for decode.

The dense serving cache ([slots, max_len, d_model] per layer) couples a
sequence's HBM footprint to the WORST-CASE length: a 16-token request
in a 2048-token slot pins 128x the memory it uses, and a long request
cannot start until a whole slot's worth of contiguous cache is free.
The vLLM PagedAttention design decouples the two:

* ONE pool of fixed-size blocks ([n_layers, num_blocks, block_size,
  d_model] for K and for V) is preallocated up front — serving never
  allocates device memory again;
* each sequence owns an ordered BLOCK TABLE of pool indices; logical
  position j lives in table[j // block_size] at offset j % block_size;
* blocks are allocated as a sequence grows past a block boundary-free
  at admit time for the whole admitted budget here, since the scheduler
  (serving/generation.py) admits only requests whose prompt+max_new
  budget fits — and returned to the free list the moment the sequence
  finishes, so long and short sequences share the pool without
  fragmentation (any free block serves any sequence; "fragmentation"
  can only exist inside a sequence's LAST partially-filled block).

Block 0 is reserved as the null/scratch block: unallocated table
entries point at it (gathers stay in-bounds; the position mask hides
the values) and inactive decode slots write into it.

PREFIX CACHING (`prefix_cache=True`): millions of users share system
prompts, so fully-filled PROMPT blocks are hash-consed by content —
block i's key is the chained digest of every prompt token through the
end of block i, so a key identifies the block's values exactly (K/V at
a position is a deterministic function of the token prefix).  A new
sequence whose leading prompt blocks hit the table SHARES those blocks
(refcount++) and the scheduler skips their prefill entirely; the share
is copy-on-write in the degenerate, zero-copy sense: shared blocks are
fully filled and the only write a sequence can aim at one (re-running
the last prompt position of a block-aligned hit) writes byte-identical
values, so no copy is ever needed.  On release, a cached block whose
refcount drops to zero is NOT freed — it parks in an LRU of
unreferenced cached blocks and is evicted (hash unregistered, block
reused) only when an allocation finds the free list empty.

This module is the HOST-side manager (free list, refcounts, hash
table, LRU, accounting); the device-side gather/scatter math lives in
models/transformer.build_lm_paged_decoder.
"""
from __future__ import annotations

import hashlib
import itertools
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import metrics as obs_metrics

__all__ = ["PagedKVCache", "KVPoolExhausted"]

_CACHE_IDS = itertools.count()
_M_BLOCKS_USED = obs_metrics.gauge(
    "paddle_tpu_serving_kv_blocks_in_use",
    "allocated KV-cache blocks (out of kv_blocks_total)", ("server",),
    always=True)
_M_BLOCKS_TOTAL = obs_metrics.gauge(
    "paddle_tpu_serving_kv_blocks_total",
    "allocatable KV-cache blocks in the preallocated pool", ("server",),
    always=True)
_M_UTIL = obs_metrics.gauge(
    "paddle_tpu_serving_kv_pool_utilization",
    "fraction of the KV block pool currently allocated", ("server",),
    always=True)
_M_PREFIX_HITS = obs_metrics.counter(
    "paddle_tpu_serving_prefix_hits_total",
    "prompt blocks served from the prefix cache (prefill skipped)",
    ("server",), always=True)
_M_PREFIX_MISSES = obs_metrics.counter(
    "paddle_tpu_serving_prefix_misses_total",
    "cacheable prompt blocks that had to be prefilled", ("server",),
    always=True)
_M_BYTES_RESIDENT = obs_metrics.gauge(
    "paddle_tpu_serving_kv_bytes_resident",
    "device bytes of KV data held by live sequences "
    "(referenced blocks x bytes per block, K+V, all layers)",
    ("server",), always=True)


class KVPoolExhausted(RuntimeError):
    """An allocation asked for more blocks than are free.  The scheduler
    treats this as admission backpressure (the request waits for blocks
    to free), never as a crash."""


def _chain_block_hashes(tokens: Sequence[int],
                        block_size: int) -> List[bytes]:
    """Chained content digests for each FULL block of `tokens`: key i
    commits to every token through position (i+1)*block_size, so equal
    keys mean equal K/V values (decode is deterministic in the prefix).
    Collision-resistant digests, not Python hash(): a collision would
    alias two different prefixes into one block — silently wrong
    tokens, not a crash."""
    keys = []
    h = b""
    for i in range(len(tokens) // block_size):
        blk = np.asarray(tokens[i * block_size:(i + 1) * block_size],
                         np.int64)
        h = hashlib.sha1(h + blk.tobytes()).digest()
        keys.append(h)
    return keys


class PagedKVCache:
    """Free-list manager over one preallocated pool of KV blocks.

    `num_blocks` is the allocatable budget (the device pool holds one
    extra reserved null block).  `server_label` ties the utilization
    series to the owning GenerationServer's metrics instance.
    `prefix_cache=True` arms block-level prefix caching (hash-consed
    full prompt blocks, refcounted sharing, LRU eviction of
    unreferenced cached blocks).  `bytes_per_block` (device bytes of
    K+V across all layers for one block) feeds the
    `paddle_tpu_serving_kv_bytes_resident` gauge."""

    def __init__(self, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int,
                 server_label: Optional[str] = None,
                 prefix_cache: bool = False,
                 bytes_per_block: int = 0):
        if num_blocks < 1:
            raise ValueError("num_blocks must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.prefix_cache = bool(prefix_cache)
        self.bytes_per_block = int(bytes_per_block)
        # device block ids 1..num_blocks (0 is the reserved null block)
        self._free: List[int] = list(range(1, self.num_blocks + 1))
        self._owned: Dict[object, List[int]] = {}
        self._ref: Dict[int, int] = {}            # block -> live refs
        self._by_hash: Dict[bytes, int] = {}      # content key -> block
        self._hash_of: Dict[int, bytes] = {}      # block -> content key
        # unreferenced cached blocks, oldest-released first (eviction
        # order); values unused
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # owner -> [(filled_end_position, key, block)] awaiting commit:
        # a freshly-allocated prompt block becomes shareable only after
        # the scheduler's cursor passes its last position (the K/V is
        # actually written) — registering earlier would let a second
        # sequence skip prefill into a still-empty block
        self._pending: Dict[object, List[Tuple[int, bytes, int]]] = {}
        self._hits = 0
        self._misses = 0
        self._lock = threading.Lock()
        self._sid = server_label or f"kv{next(_CACHE_IDS)}"
        self._m_used = _M_BLOCKS_USED.labels(server=self._sid)
        self._m_total = _M_BLOCKS_TOTAL.labels(server=self._sid)
        self._m_util = _M_UTIL.labels(server=self._sid)
        self._m_hits = _M_PREFIX_HITS.labels(server=self._sid)
        self._m_misses = _M_PREFIX_MISSES.labels(server=self._sid)
        self._m_bytes = _M_BYTES_RESIDENT.labels(server=self._sid)
        self._m_total.set(self.num_blocks)
        self._publish()

    # -- accounting ---------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        """Blocks an allocation can claim RIGHT NOW: the free list plus
        unreferenced cached blocks (evictable).  Admission math and the
        pool-drained invariants see cached-but-idle memory as free."""
        with self._lock:
            return len(self._free) + len(self._lru)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    @property
    def cached_blocks(self) -> int:
        """Blocks currently registered in the prefix hash table
        (referenced or parked in the LRU)."""
        with self._lock:
            return len(self._by_hash)

    def utilization(self) -> float:
        return self.used_blocks / self.num_blocks

    def prefix_stats(self) -> Dict[str, int]:
        with self._lock:
            return {"prefix_hits": self._hits,
                    "prefix_misses": self._misses,
                    "kv_blocks_cached": len(self._by_hash)}

    def blocks_for(self, num_positions: int) -> int:
        """Blocks needed to hold `num_positions` KV entries."""
        return -(-int(num_positions) // self.block_size)

    def prompt_keys(self, prompt_tokens: Sequence[int]) -> List[bytes]:
        """Precompute the prompt's chained block keys (submit-time
        memoization hook: the scheduler re-checks a blocked queue head
        every tick, and re-hashing a long system prompt per tick is
        wasted host work under the cache lock)."""
        return _chain_block_hashes(prompt_tokens, self.block_size)

    def _keys(self, prompt_tokens, prompt_keys) -> List[bytes]:
        if not self.prefix_cache:
            return []
        if prompt_keys is not None:
            return prompt_keys
        if prompt_tokens is None:
            return []
        return _chain_block_hashes(prompt_tokens, self.block_size)

    def can_admit(self, num_positions: int,
                  prompt_tokens: Optional[Sequence[int]] = None,
                  prompt_keys: Optional[List[bytes]] = None) -> bool:
        n = self.blocks_for(num_positions)
        if n > self.max_blocks_per_seq:
            return False
        keys = self._keys(prompt_tokens, prompt_keys)
        with self._lock:
            hits, lru_hits = self._count_hits_locked(keys)
            # hit blocks parked in the LRU are RESURRECTED by the
            # allocation, not consumed as fresh supply — counting them
            # on both sides would admit a request allocate_prefix
            # cannot actually serve
            avail = len(self._free) + len(self._lru) - lru_hits
            return n - hits <= avail

    def _count_hits_locked(self, keys) -> Tuple[int, int]:
        """(leading hit blocks, how many of those sit in the LRU)."""
        hits = lru_hits = 0
        for key in keys:
            blk = self._by_hash.get(key)
            if blk is None:
                break           # a hit run must be prefix-contiguous
            hits += 1
            if blk in self._lru:
                lru_hits += 1
        return hits, lru_hits

    def _publish(self):
        used = self.num_blocks - len(self._free) - len(self._lru)
        self._m_used.set(used)
        self._m_util.set(used / self.num_blocks)
        if self.bytes_per_block:
            self._m_bytes.set(used * self.bytes_per_block)

    # -- alloc/free ---------------------------------------------------------
    def _take_block_locked(self) -> Optional[int]:
        """One allocatable block: free list first, else evict the
        least-recently-released unreferenced cached block (its hash is
        unregistered — the content is about to be overwritten)."""
        if self._free:
            return self._free.pop()
        if self._lru:
            blk, _ = self._lru.popitem(last=False)
            key = self._hash_of.pop(blk)
            self._by_hash.pop(key, None)
            return blk
        return None

    def allocate(self, owner, num_positions: int) -> np.ndarray:
        """Allocate blocks for `num_positions` under `owner` (one admit
        = one owner, usually the sequence object) and return the padded
        block table [max_blocks_per_seq] int32 (tail entries 0 → the
        null block)."""
        return self.allocate_prefix(owner, num_positions)[0]

    def allocate_prefix(self, owner, num_positions: int,
                        prompt_tokens: Optional[Sequence[int]] = None,
                        prompt_keys: Optional[List[bytes]] = None
                        ) -> Tuple[np.ndarray, int]:
        """Allocate like `allocate`, sharing leading fully-filled
        prompt blocks already in the prefix cache.  Returns (table,
        cached_positions): the first `cached_positions` logical
        positions already hold this prompt's K/V — the scheduler starts
        the cursor there and skips their prefill."""
        n = self.blocks_for(num_positions)
        if n > self.max_blocks_per_seq:
            raise ValueError(
                f"{num_positions} positions need {n} blocks > "
                f"max_blocks_per_seq {self.max_blocks_per_seq}")
        keys = self._keys(prompt_tokens, prompt_keys)
        with self._lock:
            if owner in self._owned:
                raise ValueError("owner already holds blocks")
            blocks: List[int] = []
            hits = 0
            for key in keys:
                blk = self._by_hash.get(key)
                if blk is None:
                    break
                blocks.append(blk)
                self._ref[blk] = self._ref.get(blk, 0) + 1
                self._lru.pop(blk, None)   # resurrect from eviction
                hits += 1
            fresh_start = len(blocks)
            while len(blocks) < n:
                blk = self._take_block_locked()
                if blk is None:
                    # roll back the shared refs: admission backpressure
                    # must leave the accounting untouched
                    for b in blocks[:fresh_start]:
                        self._release_block_locked(b)
                    for b in blocks[fresh_start:]:
                        self._ref.pop(b, None)
                        self._free.append(b)
                    raise KVPoolExhausted(
                        f"need {n} KV blocks, "
                        f"{len(self._free) + len(self._lru)} free "
                        f"(pool {self.num_blocks})")
                self._ref[blk] = 1
                blocks.append(blk)
            self._owned[owner] = blocks
            if keys:
                self._hits += hits
                self._misses += len(keys) - hits
                # freshly-allocated FULL prompt blocks become shareable
                # once commit_prefix sees the cursor pass their end
                self._pending[owner] = [
                    ((i + 1) * self.block_size, keys[i], blocks[i])
                    for i in range(hits, len(keys))]
            self._publish()
        if hits:
            self._m_hits.inc(hits)
        if len(keys) - hits:
            self._m_misses.inc(len(keys) - hits)
        table = np.zeros(self.max_blocks_per_seq, np.int32)
        table[:n] = blocks
        return table, hits * self.block_size

    def commit_prefix(self, owner, filled_upto: int) -> None:
        """Register `owner`'s pending prompt blocks whose last position
        is now < `filled_upto` (the scheduler's cursor: every position
        below it has its K/V written).  Idempotent; a key another
        sequence committed first keeps the FIRST block (this owner's
        copy stays private — identical content, never aliased)."""
        with self._lock:
            pend = self._pending.get(owner)
            if not pend:
                return
            remaining = []
            for end, key, blk in pend:
                if end <= filled_upto:
                    if key not in self._by_hash:
                        self._by_hash[key] = blk
                        self._hash_of[blk] = key
                else:
                    remaining.append((end, key, blk))
            if remaining:
                self._pending[owner] = remaining
            else:
                self._pending.pop(owner, None)

    def _release_block_locked(self, blk: int) -> None:
        r = self._ref.get(blk, 0) - 1
        if r > 0:
            self._ref[blk] = r
            return
        self._ref.pop(blk, None)
        if blk in self._hash_of:
            self._lru[blk] = None      # park: evictable, still cached
        else:
            self._free.append(blk)

    def release(self, owner) -> None:
        """Drop `owner`'s references (idempotent — a sequence evicted
        twice must not double-free).  Shared blocks survive while any
        other sequence references them; cached blocks park in the LRU
        instead of freeing."""
        with self._lock:
            blocks = self._owned.pop(owner, None)
            self._pending.pop(owner, None)
            if blocks:
                for blk in blocks:
                    self._release_block_locked(blk)
                self._publish()

    def flush_prefix(self) -> None:
        """Invalidate every cached prefix block: cached K/V is keyed by
        token content ONLY, so it is valid for exactly one parameter
        version — a checkpoint hot swap MUST flush or post-swap
        requests would attend over the old checkpoint's K/V.  Parked
        (unreferenced) blocks return to the free list; blocks still
        referenced by live sequences merely lose their registration
        and free normally on release."""
        with self._lock:
            for blk in list(self._lru):
                self._free.append(blk)
            self._lru.clear()
            self._by_hash.clear()
            self._hash_of.clear()
            self._pending.clear()
            self._publish()

    def refcount(self, block: int) -> int:
        """Live references to `block` (testing/introspection)."""
        with self._lock:
            return self._ref.get(int(block), 0)

    def close(self):
        """Reclaim this pool's registry series (server churn must not
        grow metric dumps without bound)."""
        for fam in (_M_BLOCKS_USED, _M_BLOCKS_TOTAL, _M_UTIL,
                    _M_PREFIX_HITS, _M_PREFIX_MISSES, _M_BYTES_RESIDENT):
            fam.remove(server=self._sid)

    def __repr__(self):
        return (f"PagedKVCache(blocks={self.num_blocks}, "
                f"block_size={self.block_size}, "
                f"free={self.free_blocks}, "
                f"prefix_cache={self.prefix_cache})")
