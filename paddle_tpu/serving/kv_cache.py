"""Paged KV-cache: block-granular memory management for decode.

The dense serving cache ([slots, max_len, d_model] per layer) couples a
sequence's HBM footprint to the WORST-CASE length: a 16-token request
in a 2048-token slot pins 128x the memory it uses, and a long request
cannot start until a whole slot's worth of contiguous cache is free.
The vLLM PagedAttention design decouples the two:

* ONE pool of fixed-size blocks ([n_layers, num_blocks, block_size,
  d_model] for K and for V) is preallocated up front — serving never
  allocates device memory again;
* each sequence owns an ordered BLOCK TABLE of pool indices; logical
  position j lives in table[j // block_size] at offset j % block_size;
* blocks are allocated as a sequence grows past a block boundary-free
  at admit time for the whole admitted budget here, since the scheduler
  (serving/generation.py) admits only requests whose prompt+max_new
  budget fits — and returned to the free list the moment the sequence
  finishes, so long and short sequences share the pool without
  fragmentation (any free block serves any sequence; "fragmentation"
  can only exist inside a sequence's LAST partially-filled block).

Block 0 is reserved as the null/scratch block: unallocated table
entries point at it (gathers stay in-bounds; the position mask hides
the values) and inactive decode slots write into it.

This module is the HOST-side manager (free list, tables, accounting);
the device-side gather/scatter math lives in
models/transformer.build_lm_paged_decoder.
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional

import numpy as np

from ..observability import metrics as obs_metrics

__all__ = ["PagedKVCache", "KVPoolExhausted"]

_CACHE_IDS = itertools.count()
_M_BLOCKS_USED = obs_metrics.gauge(
    "paddle_tpu_serving_kv_blocks_in_use",
    "allocated KV-cache blocks (out of kv_blocks_total)", ("server",),
    always=True)
_M_BLOCKS_TOTAL = obs_metrics.gauge(
    "paddle_tpu_serving_kv_blocks_total",
    "allocatable KV-cache blocks in the preallocated pool", ("server",),
    always=True)
_M_UTIL = obs_metrics.gauge(
    "paddle_tpu_serving_kv_pool_utilization",
    "fraction of the KV block pool currently allocated", ("server",),
    always=True)


class KVPoolExhausted(RuntimeError):
    """An allocation asked for more blocks than are free.  The scheduler
    treats this as admission backpressure (the request waits for blocks
    to free), never as a crash."""


class PagedKVCache:
    """Free-list manager over one preallocated pool of KV blocks.

    `num_blocks` is the allocatable budget (the device pool holds one
    extra reserved null block).  `server_label` ties the utilization
    series to the owning GenerationServer's metrics instance.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int,
                 server_label: Optional[str] = None):
        if num_blocks < 1:
            raise ValueError("num_blocks must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        # device block ids 1..num_blocks (0 is the reserved null block)
        self._free: List[int] = list(range(1, self.num_blocks + 1))
        self._owned: Dict[object, List[int]] = {}
        self._lock = threading.Lock()
        self._sid = server_label or f"kv{next(_CACHE_IDS)}"
        self._m_used = _M_BLOCKS_USED.labels(server=self._sid)
        self._m_total = _M_BLOCKS_TOTAL.labels(server=self._sid)
        self._m_util = _M_UTIL.labels(server=self._sid)
        self._m_total.set(self.num_blocks)
        self._publish()

    # -- accounting ---------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    def utilization(self) -> float:
        return self.used_blocks / self.num_blocks

    def blocks_for(self, num_positions: int) -> int:
        """Blocks needed to hold `num_positions` KV entries."""
        return -(-int(num_positions) // self.block_size)

    def can_admit(self, num_positions: int) -> bool:
        n = self.blocks_for(num_positions)
        if n > self.max_blocks_per_seq:
            return False
        with self._lock:
            return n <= len(self._free)

    def _publish(self):
        self._m_used.set(self.num_blocks - len(self._free))
        self._m_util.set((self.num_blocks - len(self._free))
                         / self.num_blocks)

    # -- alloc/free ---------------------------------------------------------
    def allocate(self, owner, num_positions: int) -> np.ndarray:
        """Allocate blocks for `num_positions` under `owner` (one admit
        = one owner, usually the sequence object) and return the padded
        block table [max_blocks_per_seq] int32 (tail entries 0 → the
        null block)."""
        n = self.blocks_for(num_positions)
        if n > self.max_blocks_per_seq:
            raise ValueError(
                f"{num_positions} positions need {n} blocks > "
                f"max_blocks_per_seq {self.max_blocks_per_seq}")
        with self._lock:
            if owner in self._owned:
                raise ValueError("owner already holds blocks")
            if n > len(self._free):
                raise KVPoolExhausted(
                    f"need {n} KV blocks, {len(self._free)} free "
                    f"(pool {self.num_blocks})")
            blocks = [self._free.pop() for _ in range(n)]
            self._owned[owner] = blocks
            self._publish()
        table = np.zeros(self.max_blocks_per_seq, np.int32)
        table[:n] = blocks
        return table

    def release(self, owner) -> None:
        """Return `owner`'s blocks to the free list (idempotent — a
        sequence evicted twice must not double-free)."""
        with self._lock:
            blocks = self._owned.pop(owner, None)
            if blocks:
                self._free.extend(blocks)
                self._publish()

    def close(self):
        """Reclaim this pool's registry series (server churn must not
        grow metric dumps without bound)."""
        for fam in (_M_BLOCKS_USED, _M_BLOCKS_TOTAL, _M_UTIL):
            fam.remove(server=self._sid)

    def __repr__(self):
        return (f"PagedKVCache(blocks={self.num_blocks}, "
                f"block_size={self.block_size}, "
                f"free={self.free_blocks})")
