"""Resident inference serving: precompiled executables + dynamic batching.

The reference serves inference through a resident C-API process
(/root/reference/paddle/capi/gradient_machine.cpp — load once, feed/
forward many) and its published CPU-inference table
(benchmark/IntelOptimizedPaddle.md) is throughput of exactly such a
resident loop.  The TPU-native analogue:

  * the model is AOT-compiled ONCE per batch-size bucket (no per-call
    Program walk, no jit-dispatch re-tracing — the executable is called
    directly);
  * a worker thread coalesces concurrently-submitted requests into one
    dispatch (dynamic batching).  Inference has no cross-sample
    coupling (batch-norm runs is_test), so K aggregated single-image
    requests compute the SAME per-request results as K separate calls —
    this is the standard TF-Serving/Triton request-aggregation design;
  * host->device transfer of the next batch overlaps the previous
    batch's device compute (the worker stages inputs, dispatches
    asynchronously, and only the caller's `result()` blocks).

Why this exists as a subsystem and not a benchmark trick: per-dispatch
overhead through a remote-device transport scales with executable size
(measured ~2.7 ms for AlexNet vs 0.03 ms for a trivial op on the same
link), so single-stream bs-1 serving is transport-bound while the chip
is ~90% idle.  Aggregation converts concurrency into device utilization
without changing any request's numerics.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Dict, Optional, Sequence

import numpy as np

from ..core.resilience import fault_injector
from ..observability import metrics as obs_metrics
from ..observability import tracing as obs_tracing
from ..reader.pipeline import stage_to_device

__all__ = ["InferenceServer", "ServerSaturated", "RequestDeadlineExceeded"]

# serving telemetry, one label per server instance.  The counters that
# back stats() are always=True (the stats() contract predates the
# PADDLE_TPU_METRICS switch); the latency/batch/queue series are gated.
_SERVER_IDS = itertools.count()
_M_REQUESTS = obs_metrics.counter(
    "paddle_tpu_serving_requests_total",
    "requests dispatched to the device", ("server",), always=True)
_M_DISPATCHES = obs_metrics.counter(
    "paddle_tpu_serving_dispatches_total",
    "coalesced device dispatches (dispatches << requests shows "
    "aggregation)", ("server",), always=True)
_M_SHED = obs_metrics.counter(
    "paddle_tpu_serving_shed_total",
    "submits rejected with ServerSaturated (queue full)",
    ("server",), always=True)
_M_DEADLINE = obs_metrics.counter(
    "paddle_tpu_serving_deadline_expired_total",
    "requests dropped because their deadline expired while queued",
    ("server",), always=True)
_M_LATENCY = obs_metrics.histogram(
    "paddle_tpu_serving_request_seconds",
    "submit -> result-delivered wall latency", ("server",))
_M_BATCH = obs_metrics.histogram(
    "paddle_tpu_serving_batch_size",
    "requests coalesced per dispatch", ("server",),
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
_M_QDEPTH = obs_metrics.gauge(
    "paddle_tpu_serving_queue_depth",
    "requests waiting in the batching queue", ("server",))


class ServerSaturated(RuntimeError):
    """The batching queue is full — graceful backpressure: the caller
    should shed load or retry later, instead of blocking unboundedly
    behind a stalled worker (subclasses RuntimeError so pre-existing
    handlers keep working)."""


class RequestDeadlineExceeded(TimeoutError):
    """A request's deadline expired while it sat in the batching queue;
    the server drops it without spending device time on it."""


class InferenceServer:
    """Resident server over one feed / one fetch inference program.

    server = InferenceServer(infer_prog, "img", predict, scope)
    fut = server.submit(img)          # [C,H,W] or [1,C,H,W] numpy
    out = fut.result()                # blocks this caller only
    server.close()

    `buckets` are the precompiled batch sizes; a coalesced batch pads up
    to the smallest bucket that fits (padding rows are a repeat of the
    last request and are sliced away before delivery).
    """

    def __init__(self, program, feed_name: str, fetch_var, scope,
                 place=None, buckets: Sequence[int] = (1, 2, 4, 8, 16),
                 window_ms: float = 0.3, max_queue: int = 1024):
        import jax

        from ..core.executor import TPUPlace, program_to_fn

        self._feed_name = feed_name
        fetch_name = getattr(fetch_var, "name", str(fetch_var))
        self._buckets = sorted(set(int(b) for b in buckets))
        self._window_s = window_ms / 1000.0
        place = place or TPUPlace()
        self._device = place.jax_device()

        fn = program_to_fn(program, [feed_name], [fetch_name])
        states = {n: jax.device_put(np.asarray(scope.find_var(n)),
                                    self._device)
                  for n in fn.state_in_names}
        key = jax.random.key(0)

        def fwd(feeds, states):
            return fn(feeds, states, key)[0][fetch_name]

        jfn = jax.jit(fwd)
        from ..core.types import np_dtype

        sample, self._dtype = None, np.dtype("float32")
        for v in program.global_block().vars.values():
            if v.name == feed_name:
                sample = tuple(int(d) for d in v.shape)
                self._dtype = np.dtype(np_dtype(v.dtype))
                break
        if sample is None:
            raise ValueError(f"no feed var {feed_name!r} in program")
        if sample and sample[0] == -1:  # data vars carry the batch dim
            sample = sample[1:]
        self._item_shape = sample
        # AOT-compile every bucket up front: serving never pays a compile
        self._compiled: Dict[int, object] = {}
        for b in self._buckets:
            spec = jax.ShapeDtypeStruct((b,) + sample, self._dtype)
            self._compiled[b] = jfn.lower(
                {feed_name: spec}, states).compile()
        self._states = states
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._stop = False
        # serializes submit vs close: without it a submit that passed
        # the stop check could enqueue AFTER close() drained the queue,
        # leaving its Future unresolved forever
        self._submit_lock = threading.Lock()
        sid = self._sid = str(next(_SERVER_IDS))
        self._m_requests = _M_REQUESTS.labels(server=sid)
        self._m_dispatches = _M_DISPATCHES.labels(server=sid)
        self._m_shed = _M_SHED.labels(server=sid)
        self._m_deadline = _M_DEADLINE.labels(server=sid)
        self._m_latency = _M_LATENCY.labels(server=sid)
        self._m_batch = _M_BATCH.labels(server=sid)
        self._m_qdepth = _M_QDEPTH.labels(server=sid)
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    # -- client side --------------------------------------------------------
    def submit(self, x, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request ([C,H,W] or [1,C,H,W]); returns a Future
        resolving to the [1, ...] fetch for this request.  With
        `deadline_ms`, a request still queued when the deadline passes is
        failed with RequestDeadlineExceeded instead of occupying a batch
        slot (load-shedding under overload); a saturated queue raises
        ServerSaturated immediately."""
        x = np.asarray(x, self._dtype)
        if x.shape == self._item_shape:
            x = x[None]
        if x.shape != (1,) + self._item_shape:
            raise ValueError(
                f"request shape {x.shape} != (1,)+{self._item_shape}")
        fut: Future = Future()
        expires = (time.monotonic() + deadline_ms / 1000.0
                   if deadline_ms is not None else None)
        item = (x, fut, expires, time.perf_counter(),
                obs_tracing.current_context())
        with self._submit_lock:
            if self._stop:
                raise RuntimeError("InferenceServer is closed")
            try:
                # non-blocking while holding the lock: a blocking put on a
                # full queue (worker stalled) would wedge every submitter
                # on the lock and deadlock close(), whose failure-drain
                # path needs the same lock
                self._q.put_nowait(item)
            except queue.Full:
                self._m_shed.inc()
                raise ServerSaturated(
                    "InferenceServer queue full "
                    f"({self._q.maxsize} pending) — backpressure: retry "
                    "later or raise max_queue") from None
        if obs_metrics.enabled():
            self._m_qdepth.set(self._q.qsize())
        return fut

    def infer(self, x, timeout: Optional[float] = None):
        """Synchronous single request (`timeout` in seconds bounds the
        wait for the result)."""
        return np.asarray(self.submit(x).result(timeout))

    def stats(self) -> Dict[str, int]:
        """Serving telemetry (a view over this server's series in the
        process metrics registry): `requests`/`dispatches` (dispatches
        << requests shows aggregation), `shed` (ServerSaturated
        rejections), `deadline_expired` (queued requests dropped at
        their deadline) and the instantaneous `queue_depth`."""
        return {"requests": int(self._m_requests.value),
                "dispatches": int(self._m_dispatches.value),
                "shed": int(self._m_shed.value),
                "deadline_expired": int(self._m_deadline.value),
                "queue_depth": self._q.qsize()}

    def close(self):
        with self._submit_lock:
            self._stop = True
        self._worker.join(timeout=5)
        # reclaim this instance's registry series (stats() keeps working
        # off the held child objects) — a process that churns servers
        # must not grow every dump without bound
        for fam in (_M_REQUESTS, _M_DISPATCHES, _M_SHED, _M_DEADLINE,
                    _M_LATENCY, _M_BATCH, _M_QDEPTH):
            fam.remove(server=self._sid)
        # fail any requests still queued — abandoning them would hang
        # callers blocked in fut.result() forever
        while True:
            try:
                _, fut, _, _, _ = self._q.get_nowait()
            except queue.Empty:
                break
            fut.set_exception(RuntimeError("InferenceServer closed"))

    # -- worker -------------------------------------------------------------
    def _expired(self, item) -> bool:
        """Shed a dead request at dequeue time: resolving its future with
        the deadline error costs nothing; batching it would spend a batch
        slot (and possibly a bigger bucket) on an answer nobody awaits."""
        _, fut, expires, _, _ = item
        if expires is None or time.monotonic() < expires:
            return False
        self._m_deadline.inc()
        # a deadline storm drains the queue HERE, not through dispatch —
        # without this update the gauge freezes at its submit-time high
        # water mark and overload reads as a permanently full queue
        if obs_metrics.enabled():
            self._m_qdepth.set(self._q.qsize())
        _deliver(fut, exception=RequestDeadlineExceeded(
            "request deadline expired while queued"))
        return True

    def _take_batch(self):
        """Block for the first request, then coalesce whatever arrives
        within the window, capped at the largest bucket."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        if self._expired(first):
            return []
        batch = [first]
        cap = self._buckets[-1]
        deadline = time.perf_counter() + self._window_s
        while len(batch) < cap:
            remain = deadline - time.perf_counter()
            if remain <= 0 and self._q.empty():
                break
            try:
                item = self._q.get(timeout=max(remain, 0))
            except queue.Empty:
                break
            if not self._expired(item):
                batch.append(item)
        return batch

    def _loop(self):
        import jax

        while not self._stop:
            batch = self._take_batch()
            if not batch:
                continue
            # chaos hook: delay rules here back the queue up, which is
            # how the saturation/deadline tests create overload; an
            # error rule fails this batch but must not kill the worker
            try:
                fault_injector().fire("serving.dispatch")
            except Exception as e:
                for _, fut, _, _, _ in batch:
                    _deliver(fut, exception=e)
                continue
            n = len(batch)
            bucket = next(b for b in self._buckets if b >= n)
            xs = [item[0] for item in batch]
            if bucket > n:  # pad with the last request, sliced away below
                xs += [xs[-1]] * (bucket - n)
            # dispatch span parents under the FIRST request's submitter
            # context (thread handoff over the queue) — one coalesced
            # dispatch belongs to many requests; the first is the one
            # whose latency it bounds
            with obs_tracing.activate(batch[0][4]), \
                    obs_tracing.span("serving.dispatch", batch=n,
                                     bucket=bucket):
                # batch assembly reuses the training pipeline's H2D
                # staging stage (same `pipeline.h2d` profiler event):
                # the transfer on this worker thread overlaps the
                # PREVIOUS dispatch's device compute; the dispatch
                # below is async
                staged = stage_to_device(np.concatenate(xs, axis=0),
                                         self._device)
                try:
                    out = self._compiled[bucket](
                        {self._feed_name: staged}, self._states)
                except Exception as e:  # deliver, don't kill the loop
                    for _, fut, _, _, _ in batch:
                        _deliver(fut, exception=e)
                    continue
            self._m_dispatches.inc()
            self._m_requests.inc(n)
            metrics_on = obs_metrics.enabled()
            if metrics_on:
                self._m_batch.observe(n)
                self._m_qdepth.set(self._q.qsize())
            for i, (_, fut, _, t0, _) in enumerate(batch):
                _deliver(fut, result=out[i:i + 1])
                if metrics_on:
                    self._m_latency.observe(time.perf_counter() - t0)


def _deliver(fut: Future, result=None, exception=None):
    """Resolve a future, tolerating client-side cancellation — a
    set_result on a cancelled Future raises InvalidStateError, which
    must not kill the worker loop (every later request would hang).
    ONLY that: a broader catch would also swallow worker bugs (a
    result the Future machinery rejects for a real reason)."""
    try:
        if exception is not None:
            fut.set_exception(exception)
        else:
            fut.set_result(result)
    except InvalidStateError:
        pass  # cancelled by the client; nothing to deliver
